"""Pipeline-parallel execution of a netconfig graph.

Partitions ``Network.connections`` into K contiguous stages at points where
the live-activation frontier is a single node (pool/flatten boundaries in a
conv net), balances stages by a FLOP estimate, and runs the body through
:func:`cxxnet_tpu.parallel.pipeline.pipeline_apply_hetero` with microbatches
drawn from the batch dim.  The trailing loss layers (self-loops, reference
``loss/loss_layer_base-inl.hpp:36``) run outside the pipeline on the
collected outputs with the full label plumbing; mid-body ``ctx.losses``
contributions (and the tail-batch loss mask they consult) are threaded
through the stage boundaries — see :func:`make_stage_fns`.

No reference counterpart — the reference's only scaling axis is data
parallelism through mshadow-ps (SURVEY.md §2.8); ``mesh = pipe:K`` extends
the same config surface to pipeline parallelism.
"""

from __future__ import annotations

from typing import Callable, List, Tuple

import jax.numpy as jnp

from ..layers.base import ForwardContext, LabelInfo
from ..layers.conv import ConvolutionLayer
from ..layers.fullc import FullConnectLayer


def _conn_cost(net, ci: int) -> float:
    """FLOP estimate for balancing (conv/fullc dominate; everything else
    counts as its output size, a bandwidth proxy)."""
    conn = net.connections[ci]
    out_shape = net.node_shapes[conn.nindex_out[0]]
    l = conn.layer
    if isinstance(l, ConvolutionLayer):
        n, co, oh, ow = out_shape
        ci_ = net.node_shapes[conn.nindex_in[0]][1]
        p = l.param
        return (2.0 * n * co * oh * ow * (ci_ // p.num_group)
                * p.kernel_height * p.kernel_width)
    if isinstance(l, FullConnectLayer):
        nin = net.node_shapes[conn.nindex_in[0]]
        return 2.0 * nin[0] * nin[1] * nin[2] * nin[3] * l.param.num_hidden
    return float(out_shape[0] * out_shape[1] * out_shape[2] * out_shape[3])


def partition_network(net, n_stage: int) -> Tuple[List[Tuple[int, int]], int]:
    """Split the graph body into ``n_stage`` contiguous connection ranges.

    Returns ``(stages, body_end)`` where ``stages`` is a list of
    ``[start, end)`` ranges over ``net.connections`` and connections from
    ``body_end`` on (the trailing loss layers) run post-pipeline.  A cut
    after connection i is legal only when exactly one produced node is
    still live (consumed later) — the single activation that crosses the
    stage boundary.
    """
    conns = net.connections
    # body = everything before the first loss layer; only TRAILING losses
    # can form the post-pipeline tail
    body_end = len(conns)
    for i, c in enumerate(conns):
        if c.layer.is_loss:
            body_end = i
            break
    assert body_end > 0, "graph partition: network has no non-loss body"
    non_loss_after = [i for i in range(body_end, len(conns))
                      if not conns[i].layer.is_loss]
    assert not non_loss_after, (
        "graph partition (pipe/remat): loss layers must all trail the "
        "network body — mid-graph auxiliary heads (e.g. "
        "googlenet(aux_heads=True)) are not partitionable; use "
        "aux_heads=False with mesh=pipe / remat")
    for c in conns[:body_end]:
        nb = c.layer.init_buffers(
            [net.node_shapes[n] for n in c.nindex_in])
        assert not nb, (
            f"graph partition (pipe/remat): layer {c.layer.type_names[0]} "
            "keeps running buffers (e.g. batch_norm moving stats); buffer "
            "updates don't thread through partitioned execution yet")

    # consumers per node over the body + the boundary into the loss tail
    last_use = {}
    for i, c in enumerate(conns):
        for n in c.nindex_in:
            last_use[n] = i
    legal = []  # cut AFTER body connection i
    for i in range(body_end - 1):
        live = set()
        for j in range(i + 1):
            for n in conns[j].nindex_out:
                if last_use.get(n, -1) > i:
                    live.add(n)
        # input nodes still needed later also cross the cut
        for n in conns[0].nindex_in:
            if last_use.get(n, -1) > i:
                live.add(n)
        if len(live) == 1:
            legal.append(i)
    # balance by prefix cost: pick the legal cut nearest each target
    costs = [_conn_cost(net, i) for i in range(body_end)]
    total = sum(costs)
    prefix = []
    acc = 0.0
    for c in costs:
        acc += c
        prefix.append(acc)
    cuts = []
    avail = list(legal)
    for k in range(1, n_stage):
        target = total * k / n_stage
        assert avail, (
            f"graph partition (pipe/remat): too few single-node cut "
            f"points for {n_stage} segments (found {len(legal)} legal "
            "cuts)")
        best = min(avail, key=lambda i: abs(prefix[i] - target))
        cuts.append(best)
        avail = [i for i in avail if i > best]
    bounds = [0] + [c + 1 for c in cuts] + [body_end]
    stages = [(bounds[i], bounds[i + 1]) for i in range(n_stage)]
    return stages, body_end


def _boundary_node(net, end: int, body_end: int) -> int:
    """The single live node crossing the cut after connection end-1."""
    if end >= body_end:
        return net.connections[body_end - 1].nindex_out[0]
    last_use = {}
    for i, c in enumerate(net.connections):
        for n in c.nindex_in:
            last_use[n] = i
    live = [n for j in range(end) for n in net.connections[j].nindex_out
            if last_use.get(n, -1) >= end]
    live = list(dict.fromkeys(live))
    assert len(live) == 1, f"cut after {end - 1} has frontier {live}"
    return live[0]


def make_stage_fns(net, stages, body_end, *, train: bool, epoch,
                   loss_scale: float, rng=None,
                   mesh=None) -> List[Callable]:
    """Build ``stage_fns[s](params, value, m)`` callables for
    :func:`pipeline_apply_hetero`.

    ``value`` is an ``(activation, aux_loss)`` pair — or an
    ``(activation, aux_loss, mask)`` triple on masked tail batches: mid-
    body layers that append to ``ctx.losses`` (the MoE Switch load-balance
    aux loss being the concrete case) must survive partitioned execution,
    so each stage folds its ``ctx.losses`` into the accumulator that rides
    along with the boundary activation, and the tail-batch loss mask rides
    along too so those layers exclude replica instances from their
    statistics exactly like the plain path.

    Each stage runs its connection range over a local node environment;
    randomness is keyed per (microbatch, stage) so dropout etc. stay
    deterministic under any pipe width.
    """
    import jax

    n_stage = len(stages)
    in_nodes = [net.connections[s0].nindex_in[0] for s0, _ in stages]
    out_nodes = [_boundary_node(net, s1, body_end) for _, s1 in stages]

    def mk(s, s0, s1):
        def fn(params, value, m):
            x, loss_acc, *rest = value
            mb_mask = rest[0] if rest else None
            ctx = ForwardContext(
                train=train,
                rng=None if rng is None
                else jax.random.fold_in(rng, m * n_stage + s),
                labels=None if mb_mask is None
                else LabelInfo(fields={}, mask=mb_mask),
                epoch=epoch, loss_scale=loss_scale, mesh=mesh)
            nodes = {in_nodes[s]: x}
            for j in range(s0, s1):
                conn = net.connections[j]
                ins = [nodes[n] for n in conn.nindex_in]
                p = params.get(conn.param_key, {})
                outs, _ = conn.layer.forward(p, {}, ins, ctx)
                for n, v in zip(conn.nindex_out, outs):
                    nodes[n] = v
            for l in ctx.losses:
                loss_acc = loss_acc + l
            return (nodes[out_nodes[s]], loss_acc, *rest)
        return fn

    return [mk(s, s0, s1) for s, (s0, s1) in enumerate(stages)]
