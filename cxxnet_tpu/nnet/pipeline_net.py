"""Pipeline-parallel execution of a netconfig graph.

Partitions ``Network.connections`` into K contiguous stages — any cut is
legal; the boundary carries the full live-activation frontier as a tuple
(single nodes at pool/flatten boundaries, multi-node frontiers across
skip connections / inception branches) — balances stages by a FLOP
estimate, and runs the body through
:func:`cxxnet_tpu.parallel.pipeline.pipeline_apply_hetero` with microbatches
drawn from the batch dim.  The trailing loss layers (self-loops, reference
``loss/loss_layer_base-inl.hpp:36``) run outside the pipeline on the
collected outputs with the full label plumbing; mid-body ``ctx.losses``
contributions (and the tail-batch loss mask they consult) are threaded
through the stage boundaries — see :func:`make_stage_fns`.

No reference counterpart — the reference's only scaling axis is data
parallelism through mshadow-ps (SURVEY.md §2.8); ``mesh = pipe:K`` extends
the same config surface to pipeline parallelism.
"""

from __future__ import annotations

from typing import Callable, List, Tuple

import jax.numpy as jnp

from ..layers.base import ForwardContext, LabelInfo, conn_scope_name
from ..layers.conv import ConvolutionLayer
from ..layers.fullc import FullConnectLayer
from .net import conn_params


def _conn_cost(net, ci: int) -> float:
    """FLOP estimate for balancing (conv/fullc dominate; everything else
    counts as its output size, a bandwidth proxy)."""
    conn = net.connections[ci]
    out_shape = net.node_shapes[conn.nindex_out[0]]
    l = conn.layer
    if isinstance(l, ConvolutionLayer):
        n, co, oh, ow = out_shape
        ci_ = net.node_shapes[conn.nindex_in[0]][1]
        p = l.param
        return (2.0 * n * co * oh * ow * (ci_ // p.num_group)
                * p.kernel_height * p.kernel_width)
    if isinstance(l, FullConnectLayer):
        nin = net.node_shapes[conn.nindex_in[0]]
        return 2.0 * nin[0] * nin[1] * nin[2] * nin[3] * l.param.num_hidden
    return float(out_shape[0] * out_shape[1] * out_shape[2] * out_shape[3])


def _last_use(net):
    lu = {}
    for i, c in enumerate(net.connections):
        for n in c.nindex_in:
            lu[n] = i
    return lu


def _graph_inputs(net) -> List[int]:
    """Nodes consumed before any connection produces them (the data node
    and any extra-data nodes)."""
    produced, inputs = set(), []
    for c in net.connections:
        for n in c.nindex_in:
            if n not in produced and n not in inputs:
                inputs.append(n)
        produced.update(c.nindex_out)
    return inputs


def frontier_nodes(net, end: int) -> List[int]:
    """Ordered list of nodes live across the cut before connection
    ``end`` (graph inputs first, then by producing connection)."""
    lu = _last_use(net)
    live = [n for n in _graph_inputs(net) if lu.get(n, -1) >= end]
    for j in range(end):
        for n in net.connections[j].nindex_out:
            if lu.get(n, -1) >= end and n not in live:
                live.append(n)
    return live


def partition_network(net, n_stage: int) -> Tuple[List[Tuple[int, int]], int]:
    """Split the graph body into ``n_stage`` contiguous connection ranges.

    Returns ``(stages, body_end)`` where ``stages`` is a list of
    ``[start, end)`` ranges over ``net.connections`` and connections from
    ``body_end`` on (the trailing loss layers) run post-pipeline.

    Any cut position is legal: the boundary carries the *frontier* — every
    node still live across the cut — as a tuple (round 3 required a
    single-live-node frontier, which ruled out inception-style branch
    regions and mid-graph aux heads entirely; VERDICT r3 item 7).  Cut
    selection balances a FLOP estimate and, among near-balanced
    candidates, prefers the narrowest frontier (fewest activations stored
    at the checkpoint boundary / rotated between pipeline stages).
    Mid-body loss layers (GoogLeNet aux heads) stay in the body; their
    loss terms thread out through the stage values (make_stage_fns).
    """
    conns = net.connections
    assert any(not c.layer.is_loss for c in conns), \
        "graph partition: network has no non-loss body"
    body_end = max(i for i, c in enumerate(conns)
                   if not c.layer.is_loss) + 1
    for c in conns[:body_end]:
        if c.layer.is_loss:
            continue
        nb = c.layer.init_buffers(
            [net.node_shapes[n] for n in c.nindex_in])
        assert not nb, (
            f"graph partition (pipe/remat): layer {c.layer.type_names[0]} "
            "keeps running buffers (e.g. batch_norm moving stats); buffer "
            "updates don't thread through partitioned execution yet")

    costs = [_conn_cost(net, i) for i in range(body_end)]
    total = sum(costs)
    prefix = []
    acc = 0.0
    for c in costs:
        acc += c
        prefix.append(acc)
    fsize = {i: len(frontier_nodes(net, i + 1))
             for i in range(body_end - 1)}
    cuts = []
    avail = list(range(body_end - 1))
    for k in range(1, n_stage):
        target = total * k / n_stage
        assert avail, (
            f"graph partition (pipe/remat): too few cut points for "
            f"{n_stage} segments ({body_end} body connections)")
        # near-balanced candidates (within a quarter stage of the
        # target): narrowest frontier wins, distance breaks ties
        tol = 0.25 * total / n_stage
        near = [i for i in avail if abs(prefix[i] - target) <= tol]
        pool = near or avail
        best = min(pool, key=lambda i: (fsize[i] if near else 0,
                                        abs(prefix[i] - target)))
        cuts.append(best)
        avail = [i for i in avail if i > best]
    bounds = [0] + [c + 1 for c in cuts] + [body_end]
    stages = [(bounds[i], bounds[i + 1]) for i in range(n_stage)]
    return stages, body_end


def make_stage_fns(net, stages, body_end, *, train: bool, epoch,
                   loss_scale: float, rng=None,
                   mesh=None) -> List[Callable]:
    """Build ``stage_fns[s](params, value, m)`` callables for
    :func:`pipeline_apply_hetero` and the remat path.

    ``value`` is ``(acts, aux_loss, extra)``:

    * ``acts`` — tuple of the frontier activations crossing the stage's
      input boundary (a bare array is accepted for a width-1 frontier);
    * ``aux_loss`` — scalar accumulator: each stage folds its
      ``ctx.losses`` in, so mid-body loss contributors (MoE load-balance
      terms, GoogLeNet aux-head softmax losses) survive partitioned
      execution;
    * ``extra`` — ``{"fields": {name: labels}, "mask": mask-or-None}``
      riding along unchanged, so mid-body loss layers see their label
      fields and tail-batch replica instances stay excluded from loss
      statistics exactly like the plain path.

    Each stage runs its connection range over a local node environment;
    randomness is keyed per (microbatch, stage) so dropout etc. stay
    deterministic under any pipe width.
    """
    import jax

    n_stage = len(stages)
    in_nodes = [frontier_nodes(net, s0) for s0, _ in stages]
    out_nodes = [frontier_nodes(net, s1) for _, s1 in stages]

    def mk(s, s0, s1):
        def fn(params, value, m):
            acts, loss_acc, extra = value
            if not isinstance(acts, tuple):
                acts = (acts,)
            fields, mb_mask = extra["fields"], extra["mask"]
            ctx = ForwardContext(
                train=train,
                rng=None if rng is None
                else jax.random.fold_in(rng, m * n_stage + s),
                labels=LabelInfo(fields=fields, mask=mb_mask)
                if fields or mb_mask is not None else None,
                epoch=epoch, loss_scale=loss_scale, mesh=mesh)
            nodes = dict(zip(in_nodes[s], acts))
            for j in range(s0, s1):
                conn = net.connections[j]
                # same attribution stamp as Network.forward: remat,
                # pipeline, and dp_overlap segments all build through
                # here, so per-op trace times keep their layer identity
                with jax.named_scope(conn_scope_name(j, conn)):
                    ins = [nodes[n] for n in conn.nindex_in]
                    p = conn_params(params, conn)
                    outs, _ = conn.layer.forward(p, {}, ins, ctx)
                    for n, v in zip(conn.nindex_out, outs):
                        nodes[n] = v
            for l in ctx.losses:
                loss_acc = loss_acc + l
            return (tuple(nodes[n] for n in out_nodes[s]), loss_acc, extra)
        return fn

    return [mk(s, s0, s1) for s, (s0, s1) in enumerate(stages)]
