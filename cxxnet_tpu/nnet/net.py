"""Network graph: layer instantiation, shape inference, functional forward.

Reference: ``NeuralNet<xpu>`` (``src/nnet/neural_net-inl.hpp:23-297``).  The
reference owns mutable node buffers and runs Forward/Backprop layer by layer
on a device stream; here the whole graph is a pure function over an SSA node
environment, traced once and compiled by XLA — backprop is jax.grad of the
summed loss terms, so there are no hand-written Backprop methods and no
per-layer stream syncs (the reference needed one per layer with updaters,
neural_net-inl.hpp:148).

Layer sharing (``share[tag]``) reuses the primary connection's layer instance
and parameter group, reproducing kSharedLayer (neural_net-inl.hpp:238-244).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from ..layers.base import ForwardContext, LabelInfo, Layer, Shape4
from ..layers.registry import create_layer
from ..layers.shape_ops import SplitLayer
from .netconfig import NetConfig

Params = Dict[str, Dict[str, jnp.ndarray]]


@dataclasses.dataclass
class Connection:
    """Binds a layer instance to node ids (reference layer.h:380-407)."""

    layer: Layer
    nindex_in: List[int]
    nindex_out: List[int]
    # parameter-group key; shared connections point at the primary's key
    param_key: str
    owns_params: bool


class Network:
    """Static graph built from a NetConfig; all state lives in pytrees."""

    def __init__(self, cfg: NetConfig, batch_size: int, dtype=jnp.float32):
        self.cfg = cfg
        self.batch_size = batch_size
        self.dtype = dtype
        self.connections: List[Connection] = []
        self.node_shapes: List[Optional[Shape4]] = [None] * cfg.num_nodes
        self._build()
        self._infer_shapes()

    # -- construction -----------------------------------------------------
    def _layer_key(self, index: int, info) -> str:
        base = info.name if info.name else info.type_name
        return f"{index:02d}-{base}"

    def _build(self) -> None:
        cfg = self.cfg
        for i, info in enumerate(cfg.layers):
            if info.is_shared:
                primary = self.connections[info.primary_layer_index]
                conn = Connection(layer=primary.layer,
                                  nindex_in=list(info.nindex_in),
                                  nindex_out=list(info.nindex_out),
                                  param_key=primary.param_key,
                                  owns_params=False)
                self.connections.append(conn)
                continue
            layer = create_layer(info.type_name)
            layer.name = info.name
            if isinstance(layer, SplitLayer):
                layer.num_out = len(info.nindex_out)
            # global keys are re-broadcast to every layer, then the layer's own
            # section (reference neural_net-inl.hpp:252-264)
            for k, v in cfg.defcfg:
                layer.set_param(k, v)
            for k, v in cfg.layercfg[i]:
                layer.set_param(k, v)
            self.connections.append(Connection(
                layer=layer, nindex_in=list(info.nindex_in),
                nindex_out=list(info.nindex_out),
                param_key=self._layer_key(i, info), owns_params=True))

    def _infer_shapes(self) -> None:
        cfg = self.cfg
        assert cfg.input_shape is not None, "input_shape must be configured"
        c, y, x = cfg.input_shape
        self.node_shapes[0] = (self.batch_size, c, y, x)
        for i in range(cfg.extra_data_num):
            ec, ey, ex = cfg.extra_shape[3 * i: 3 * i + 3]
            self.node_shapes[1 + i] = (self.batch_size, ec, ey, ex)
        for conn in self.connections:
            in_shapes = []
            for nid in conn.nindex_in:
                assert self.node_shapes[nid] is not None, (
                    f"node {cfg.node_names[nid]!r} used before being produced")
                in_shapes.append(self.node_shapes[nid])
            out_shapes = conn.layer.infer_shapes(in_shapes)
            assert len(out_shapes) == len(conn.nindex_out), (
                f"layer {conn.layer.type_names[0]} produced {len(out_shapes)} "
                f"outputs for {len(conn.nindex_out)} output nodes")
            for nid, s in zip(conn.nindex_out, out_shapes):
                self.node_shapes[nid] = s

    # -- state ------------------------------------------------------------
    def init_params(self, key: jax.Array) -> Params:
        params: Params = {}
        for i, conn in enumerate(self.connections):
            if not conn.owns_params:
                continue
            sub = jax.random.fold_in(key, i)
            in_shapes = [self.node_shapes[n] for n in conn.nindex_in]
            p = conn.layer.init_params(sub, in_shapes, self.dtype)
            if p:
                params[conn.param_key] = p
        return params

    def init_buffers(self) -> Params:
        buffers: Params = {}
        for conn in self.connections:
            if not conn.owns_params:
                continue
            in_shapes = [self.node_shapes[n] for n in conn.nindex_in]
            b = conn.layer.init_buffers(in_shapes)
            if b:
                buffers[conn.param_key] = b
        return buffers

    # -- forward ------------------------------------------------------------
    def forward(self, params: Params, buffers: Params,
                inputs: Dict[int, jnp.ndarray], ctx: ForwardContext,
                until: Optional[int] = None
                ) -> Tuple[List[Optional[jnp.ndarray]], Params]:
        """Run all connections in declaration order.

        Returns (node value list indexed by node id, updated buffers).
        Node values are SSA: self-loop layers rebind their node's entry.
        ``until`` stops BEFORE connection index ``until`` — the decode
        engine uses it to read raw LM-head logits without running the
        softmax_seq self-loop that would rebind the logits node.
        """
        from .. import engine
        from ..layers.base import conn_scope_name, materialize
        nodes: List[Optional[jnp.ndarray]] = [None] * self.cfg.num_nodes
        for nid, v in inputs.items():
            nodes[nid] = v.astype(self.dtype) if v.dtype != self.dtype else v
        new_buffers = dict(buffers)
        fuse = getattr(self, "fuse_groups", None)
        fuse_skip = getattr(self, "fuse_skip", frozenset())
        virtual = engine.opts.concat_virtual == "1"
        for i, conn in enumerate(self.connections):
            if until is not None and i >= until:
                break
            if i in fuse_skip:
                continue
            # layer-attribution stamp: HLO op metadata (and so the
            # profiler trace) carries this connection's identity through
            # forward AND the jax.grad transpose (monitor/attribution.py
            # joins per-op device times back to it).  Metadata only: the
            # computation and the lowered program are unchanged, so the
            # monitor=0 HLO-equality guarantee holds
            with jax.named_scope(conn_scope_name(i, conn)):
                if fuse and i in fuse:
                    self._forward_fused(fuse[i], params, nodes)
                    continue
                if virtual and self._virtual_forward(conn, params, nodes):
                    continue
                ins = [materialize(nodes[n]) for n in conn.nindex_in]
                p = conn_params(params, conn)
                b = new_buffers.get(conn.param_key, {})
                outs, nb = conn.layer.forward(p, b, ins, ctx)
                # shared connections update the primary's buffer group
                # too: the next invocation reads the chained update (last
                # write wins)
                if nb:
                    new_buffers[conn.param_key] = nb
                for n, v in zip(conn.nindex_out, outs):
                    nodes[n] = v
        return nodes, new_buffers

    def _virtual_forward(self, conn, params, nodes) -> bool:
        """``concat_virtual = 1``: execute ``conn`` on virtual channel
        segments where the layer is segment-aware; return False to fall
        back to the materializing path.  ch_concat PRODUCES a ChSegs;
        split replicates it; channelwise pools map over segments (concat
        commutes with them); a conv consumes it as a sum of K-sliced
        convs (conv(concat(x_i), W) == sum_i conv(x_i, W[:, K_i])) — the
        inception module chain then never materializes its concats."""
        from ..layers.base import ChSegs
        from ..layers.conv import (AvgPoolingLayer, ConvolutionLayer,
                                   MaxPoolingLayer, SumPoolingLayer)
        from ..layers.shape_ops import ChConcatLayer, SplitLayer
        from ..ops import nn as N
        l = conn.layer
        if type(l) is ChConcatLayer and len(conn.nindex_out) == 1:
            segs = []
            for n in conn.nindex_in:
                v = nodes[n]
                segs.extend(v.segs if isinstance(v, ChSegs) else [v])
            nodes[conn.nindex_out[0]] = ChSegs(segs)
            return True
        if len(conn.nindex_in) != 1 or len(conn.nindex_out) == 0:
            return False
        v = nodes[conn.nindex_in[0]]
        if not isinstance(v, ChSegs):
            return False
        if type(l) is SplitLayer:
            for n in conn.nindex_out:
                nodes[n] = v
            return True
        if (type(l) is ConvolutionLayer and l.param.num_group == 1
                and not l.space_to_depth and not l.s2d_input):
            p = l.param
            pg = params[conn.param_key]
            out = _conv_over_segs(v.segs, pg["wmat"], p.stride,
                                  p.pad_y, p.pad_x)
            if "bias" in pg and not l.defer_bias:
                out = out + pg["bias"].astype(out.dtype).reshape(1, -1, 1, 1)
            nodes[conn.nindex_out[0]] = out
            return True
        if (type(l) in (MaxPoolingLayer, AvgPoolingLayer, SumPoolingLayer)
                and getattr(l, "deferred_bias_key", None) is None):
            p = l.param
            fn = {MaxPoolingLayer: N.max_pool2d, AvgPoolingLayer:
                  N.avg_pool2d, SumPoolingLayer: N.sum_pool2d}[type(l)]
            segs = [fn(s, p.kernel_height, p.kernel_width, p.stride,
                       p.pad_y, p.pad_x) for s in v.segs]
            if getattr(l, "relu_after", False):
                from ..layers.activation import apply_relu
                segs = [apply_relu(s) for s in segs]
            nodes[conn.nindex_out[0]] = ChSegs(segs)
            return True
        return False

    def _forward_fused(self, members: List[int], params, nodes) -> None:
        """Run a sibling-conv fusion group (``conv_sibling_fuse = 1``) as
        ONE convolution: the members share an input node and geometry, so
        their weights concatenate along the output-channel dim (inception
        modules run three 1x1 reduce convs on the same tensor — fusing
        turns 3 lane-underfilled MXU calls + 3 weight prefetches into one
        well-tiled call; autodiff slices the fused wgrad back, so each
        member keeps its own parameter group, updater state, and
        checkpoint layout).  Trainer peephole: _fuse_sibling_convs."""
        from ..layers.base import ChSegs
        from ..ops import nn as N
        mconns = [self.connections[j] for j in members]
        x = nodes[mconns[0].nindex_in[0]]
        p0 = mconns[0].layer.param
        w = jnp.concatenate(
            [params[c.param_key]["wmat"] for c in mconns], axis=0)
        if isinstance(x, ChSegs):
            out = _conv_over_segs(x.segs, w, p0.stride, p0.pad_y, p0.pad_x)
        else:
            out = N.conv2d(x, w, stride=p0.stride, pad_y=p0.pad_y,
                           pad_x=p0.pad_x, num_group=1)
        if "bias" in params[mconns[0].param_key]:
            b = jnp.concatenate(
                [params[c.param_key]["bias"] for c in mconns], axis=0)
            out = out + b.astype(out.dtype).reshape(1, -1, 1, 1)
        off = 0
        for c in mconns:
            co = c.layer.param.num_channel
            nodes[c.nindex_out[0]] = out[:, off:off + co]
            off += co

    # -- utilities ----------------------------------------------------------
    def node_id(self, name: str) -> int:
        """Resolve a node by name, or "top[-k]" pseudo-names
        (reference nnet_impl-inl.hpp:204-215)."""
        if name.startswith("top[") and name.endswith("]"):
            k = int(name[4:-1])
            # top[-1] = last node produced
            last = self.connections[-1].nindex_out[-1]
            return last + 1 + k if k < 0 else k
        if name in self.cfg.node_name_map:
            return self.cfg.node_name_map[name]
        raise KeyError(f"unknown node name {name!r}")

    @property
    def final_node(self) -> int:
        return self.connections[-1].nindex_out[-1]

    def describe(self) -> str:
        lines = []
        for i, conn in enumerate(self.connections):
            ins = ",".join(self.cfg.node_names[n] for n in conn.nindex_in)
            outs = ",".join(self.cfg.node_names[n] for n in conn.nindex_out)
            shapes = [self.node_shapes[n] for n in conn.nindex_out]
            share = " (shared)" if not conn.owns_params else ""
            lines.append(f"{i:3d} {conn.layer.type_names[0]:>20s}{share} "
                         f"[{ins} -> {outs}] out={shapes}")
        return "\n".join(lines)


def _conv_over_segs(segs, w, stride, pad_y, pad_x):
    """conv(concat(segs), w) as a sum of K-sliced convs — the consumer
    side of the virtual concat (autodiff then delivers each segment's
    input gradient directly, replacing the concat-grad slice-split)."""
    from ..ops import nn as N
    out, off = None, 0
    for s in segs:
        ci = s.shape[1]
        o = N.conv2d(s, w[:, off:off + ci], stride=stride,
                     pad_y=pad_y, pad_x=pad_x, num_group=1)
        out = o if out is None else out + o
        off += ci
    assert off == w.shape[1], (off, w.shape)
    return out


def iter_param_leaves(params):
    """Flatten a params/grads pytree into ``(name, leaf)`` pairs, naming
    leaves ``"<param_key>/<tag>"`` (nested pairtest groups join their tag
    path with ``:``, matching get_weight's addressing).  Deterministic
    order (dict insertion) so monitor records line up across steps."""
    out = []

    def walk(group, path):
        for tag, p in group.items():
            if isinstance(p, dict):
                walk(p, f"{path}:{tag}")
            else:
                out.append((f"{path}:{tag}", p))

    for pkey, group in params.items():
        for tag, p in group.items():
            if isinstance(p, dict):
                walk(p, f"{pkey}/{tag}")
            else:
                out.append((f"{pkey}/{tag}", p))
    return out


def conn_params(params, conn):
    """Per-connection parameter view.  A max pool carrying a deferred
    conv bias (the trainer's relu/bias->pool reorder) reads the bias
    from the conv's group under the key "deferred_bias" — the parameter
    stays at its original key, so gradients, the updater, sharding, and
    checkpoints are untouched."""
    p = params.get(conn.param_key, {})
    dk = getattr(conn.layer, "deferred_bias_key", None)
    if dk is not None:
        p = dict(p)
        p["deferred_bias"] = params[dk]["bias"]
    return p
