"""Network structure configuration: the ``netconfig=start/end`` +
``layer[from->to] = type:name`` declaration language.

Reference: ``src/nnet/nnet_config.h`` (Configure :207-289, GetLayerInfo
:303-360).  Parity covers:

* node name/index maps seeded with node 0 = "in" (and "0");
* ``layer[+1]`` auto-node, ``layer[+0]`` self-loop, ``layer[+1:tag]`` named
  output node;
* ``layer[a,b->c]`` comma-separated multi-node connections;
* ``share[tag]`` layers referencing a primary layer by name;
* per-layer config capture (keys after a ``layer[..]`` line belong to that
  layer until the next ``layer[..]``/``netconfig=end``);
* ``label_vec[a,b)`` multi-label field ranges and ``extra_data_num`` /
  ``extra_data_shape[i]`` side inputs;
* ``input_shape = c,y,x``.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, List, Optional, Tuple

from ..utils.config import ConfigError, ConfigPairs


@dataclasses.dataclass
class LayerInfo:
    type_name: str
    name: str = ""
    nindex_in: List[int] = dataclasses.field(default_factory=list)
    nindex_out: List[int] = dataclasses.field(default_factory=list)
    # for share[tag] layers: index of the primary layer whose params we share
    primary_layer_index: int = -1

    @property
    def is_shared(self) -> bool:
        return self.primary_layer_index >= 0


_LAYER_PLUS = re.compile(r"^layer\[\+(\d+)(?::([^\]]+))?\]$")
_LAYER_ARROW = re.compile(r"^layer\[([^\]>]+)->([^\]]+)\]$")
_LABEL_VEC = re.compile(r"^label_vec\[(\d+),(\d+)\)$")
_EXTRA_SHAPE = re.compile(r"^extra_data_shape\[(\d+)\]$")
_SHARE = re.compile(r"^share\[([^\]]+)\]$")


class NetConfig:
    """Parsed network structure + captured per-layer / global config."""

    def __init__(self) -> None:
        self.node_names: List[str] = ["in"]
        self.node_name_map: Dict[str, int] = {"in": 0, "0": 0}
        self.layers: List[LayerInfo] = []
        self.layer_name_map: Dict[str, int] = {}
        self.layercfg: List[ConfigPairs] = []
        self.defcfg: ConfigPairs = []
        self.input_shape: Optional[Tuple[int, int, int]] = None  # (c, y, x)
        self.updater_type: str = "sgd"
        self.sync_type: str = ""
        # label ranges: field name -> (start, end) columns in the label vector
        self.label_range: List[Tuple[int, int]] = []
        self.label_name_map: Dict[str, int] = {}
        self.extra_data_num: int = 0
        self.extra_shape: List[int] = []

    # -- label field helpers ---------------------------------------------
    def label_fields(self) -> List[Tuple[str, int, int]]:
        """(name, start, end) per label field; default single field "label"."""
        if not self.label_range:
            return [("label", 0, 1)]
        out = []
        for name, idx in sorted(self.label_name_map.items(), key=lambda kv: kv[1]):
            a, b = self.label_range[idx]
            out.append((name, a, b))
        return out

    def label_width(self) -> int:
        return max(e for _, _, e in self.label_fields())

    # -- parsing ----------------------------------------------------------
    def _get_node_index(self, name: str, alloc_unknown: bool) -> int:
        name = name.strip()
        if name in self.node_name_map:
            return self.node_name_map[name]
        if not alloc_unknown:
            raise ConfigError(
                f"undefined node name {name!r}: a layer's input node must be the "
                "output of an earlier layer")
        idx = len(self.node_names)
        self.node_names.append(name)
        self.node_name_map[name] = idx
        return idx

    def _parse_layer_line(self, key: str, val: str, top_node: int,
                          layer_index: int) -> LayerInfo:
        info = LayerInfo(type_name="")
        m = _LAYER_PLUS.match(key)
        if m:
            inc, tag = int(m.group(1)), m.group(2)
            if top_node < 0:
                raise ConfigError(
                    "layer[+1] used after a layer with multiple outputs; "
                    "use layer[in->out] instead")
            info.nindex_in.append(top_node)
            if tag is not None:
                info.nindex_out.append(self._get_node_index(tag, True))
            elif inc == 0:
                info.nindex_out.append(top_node)  # self-loop
            else:
                info.nindex_out.append(
                    self._get_node_index(f"!node-after-{top_node}", True))
        else:
            m = _LAYER_ARROW.match(key)
            if m is None:
                raise ConfigError(f"invalid layer declaration {key!r}")
            for tok in m.group(1).split(","):
                info.nindex_in.append(self._get_node_index(tok, False))
            for tok in m.group(2).split(","):
                info.nindex_out.append(self._get_node_index(tok, True))
        # value: "type" or "type:name"
        if ":" in val and not val.startswith("share"):
            tname, lname = val.split(":", 1)
        else:
            sm = _SHARE.match(val.split(":", 1)[0])
            if sm or val.startswith("share"):
                # share[tag] or share[tag]:name
                if ":" in val:
                    head, lname = val.split(":", 1)
                else:
                    head, lname = val, ""
                sm = _SHARE.match(head)
                if sm is None:
                    raise ConfigError(
                        "shared layer must specify the tag of the layer to "
                        "share with: share[tag]")
                tag = sm.group(1)
                if tag not in self.layer_name_map:
                    raise ConfigError(
                        f"shared layer tag {tag!r} is not defined before")
                info.primary_layer_index = self.layer_name_map[tag]
                info.type_name = "share"
                if lname:
                    self.layer_name_map[lname] = layer_index
                    info.name = lname
                return info
            tname, lname = val, ""
        info.type_name = tname
        if lname:
            if lname in self.layer_name_map and self.layer_name_map[lname] != layer_index:
                raise ConfigError(f"duplicate layer name {lname!r}")
            self.layer_name_map[lname] = layer_index
            info.name = lname
        return info

    def configure(self, cfg: ConfigPairs) -> None:
        netcfg_mode = 0
        cfg_top_node = 0
        cfg_layer_index = 0
        for name, val in cfg:
            if name == "extra_data_num":
                self.extra_data_num = int(val)
                for i in range(self.extra_data_num):
                    nm = f"in_{i + 1}"
                    if nm not in self.node_name_map:
                        self._get_node_index(nm, True)
                continue
            m = _EXTRA_SHAPE.match(name)
            if m:
                dims = [int(t) for t in val.split(",")]
                if len(dims) != 3:
                    raise ConfigError("extra data shape config incorrect")
                self.extra_shape.extend(dims)
                continue
            if name == "input_shape":
                dims = [int(t) for t in val.split(",")]
                if len(dims) != 3:
                    raise ConfigError(
                        "input_shape must be three comma-separated ints c,y,x")
                self.input_shape = tuple(dims)
            if netcfg_mode != 2:
                if name == "updater":
                    self.updater_type = val
                if name == "sync":
                    self.sync_type = val
                lm = _LABEL_VEC.match(name)
                if lm:
                    a, b = int(lm.group(1)), int(lm.group(2))
                    self.label_range.append((a, b))
                    self.label_name_map[val] = len(self.label_range) - 1
            if name == "netconfig" and val == "start":
                netcfg_mode = 1
                continue
            if name == "netconfig" and val == "end":
                netcfg_mode = 0
                continue
            if name.startswith("layer["):
                info = self._parse_layer_line(name, val, cfg_top_node,
                                              cfg_layer_index)
                netcfg_mode = 2
                assert len(self.layers) == cfg_layer_index, "NetConfig inconsistent"
                self.layers.append(info)
                self.layercfg.append([])
                if len(info.nindex_out) == 1:
                    cfg_top_node = info.nindex_out[0]
                else:
                    cfg_top_node = -1
                cfg_layer_index += 1
                continue
            if netcfg_mode == 2:
                if self.layers[cfg_layer_index - 1].is_shared:
                    raise ConfigError(
                        "do not set parameters on a shared layer; set them on "
                        "the primary layer")
                self.layercfg[cfg_layer_index - 1].append((name, val))
            else:
                self.defcfg.append((name, val))
        self.num_nodes = 0
        for info in self.layers:
            for j in info.nindex_in + info.nindex_out:
                self.num_nodes = max(self.num_nodes, j + 1)
        if self.num_nodes != len(self.node_names):
            raise ConfigError("num_nodes inconsistent with node_names")

    # -- (de)serialization for checkpoints -------------------------------
    def to_dict(self) -> dict:
        return {
            "node_names": self.node_names,
            "layers": [dataclasses.asdict(l) for l in self.layers],
            "layer_name_map": self.layer_name_map,
            "layercfg": self.layercfg,
            "defcfg": self.defcfg,
            "input_shape": self.input_shape,
            "updater_type": self.updater_type,
            "label_range": self.label_range,
            "label_name_map": self.label_name_map,
            "extra_data_num": self.extra_data_num,
            "extra_shape": self.extra_shape,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "NetConfig":
        nc = cls()
        nc.node_names = list(d["node_names"])
        nc.node_name_map = {n: i for i, n in enumerate(nc.node_names)}
        nc.node_name_map["0"] = 0
        nc.layers = [LayerInfo(**l) for l in d["layers"]]
        nc.layer_name_map = dict(d["layer_name_map"])
        nc.layercfg = [[tuple(p) for p in lc] for lc in d["layercfg"]]
        nc.defcfg = [tuple(p) for p in d["defcfg"]]
        nc.input_shape = tuple(d["input_shape"]) if d["input_shape"] else None
        nc.updater_type = d["updater_type"]
        nc.label_range = [tuple(r) for r in d["label_range"]]
        nc.label_name_map = dict(d["label_name_map"])
        nc.extra_data_num = d["extra_data_num"]
        nc.extra_shape = list(d["extra_shape"])
        nc.num_nodes = len(nc.node_names)
        return nc
