from .net import Connection, Network
from .netconfig import LayerInfo, NetConfig
from .trainer import NetTrainer

__all__ = ["Connection", "Network", "LayerInfo", "NetConfig", "NetTrainer"]
