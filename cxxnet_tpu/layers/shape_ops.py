"""Shape/topology layers: flatten, split, concat, ch_concat, maxout.

Reference: ``src/layer/flatten_layer-inl.hpp``, ``split_layer-inl.hpp``,
``concat_layer-inl.hpp`` (template dim 3 = flat-feature concat, dim 1 =
channel concat, max 4 inputs).  ``maxout`` has an enum/name in the reference
but no factory case; implemented here for real (channel-group max).
"""

from __future__ import annotations

from typing import List

import jax.numpy as jnp

from .base import ForwardContext, Layer, Params, Shape4


class FlattenLayer(Layer):
    """(n,c,h,w) -> (n,1,1,c*h*w) (flatten_layer-inl.hpp:19-22)."""

    type_names = ("flatten",)

    def infer_shapes(self, in_shapes: List[Shape4]) -> List[Shape4]:
        assert len(in_shapes) == 1, "flatten: 1-1 connection only"
        n, c, h, w = in_shapes[0]
        return [(n, 1, 1, c * h * w)]

    def forward(self, params, buffers, inputs, ctx):
        self.check_n_inputs(inputs, 1)
        x = inputs[0]
        return [x.reshape(x.shape[0], 1, 1, -1)], buffers


class SplitLayer(Layer):
    """1 -> N copy forward; gradients sum automatically under jax.grad
    (split_layer-inl.hpp:24-44)."""

    type_names = ("split",)

    def __init__(self):
        super().__init__()
        self.num_out = 2  # overridden by graph wiring

    def infer_shapes(self, in_shapes: List[Shape4]) -> List[Shape4]:
        assert len(in_shapes) == 1, "split: single input only"
        return [in_shapes[0]] * self.num_out

    def forward(self, params, buffers, inputs, ctx):
        self.check_n_inputs(inputs, 1)
        return [inputs[0]] * self.num_out, buffers


class ConcatLayer(Layer):
    """N -> 1 concat along the flat-feature axis (dim 3)
    (concat_layer-inl.hpp, template dim=3; reference caps at 4 inputs)."""

    type_names = ("concat",)
    concat_axis = 3

    def infer_shapes(self, in_shapes: List[Shape4]) -> List[Shape4]:
        assert 2 <= len(in_shapes) <= 4, "concat: supports 2..4 inputs"
        base = list(in_shapes[0])
        total = 0
        for s in in_shapes:
            for ax in range(4):
                if ax != self.concat_axis:
                    assert s[ax] == in_shapes[0][ax], \
                        f"concat: non-concat dims must match, {s} vs {in_shapes[0]}"
            total += s[self.concat_axis]
        base[self.concat_axis] = total
        return [tuple(base)]

    def forward(self, params, buffers, inputs, ctx):
        self.check_n_inputs(inputs, 2, 4)
        return [jnp.concatenate(inputs, axis=self.concat_axis)], buffers


class ChConcatLayer(ConcatLayer):
    """Channel-axis concat (concat_layer template dim=1)."""

    type_names = ("ch_concat",)
    concat_axis = 1


class MaxoutLayer(Layer):
    """Maxout over channel groups: (n, c, h, w) -> (n, c/k, h, w) taking the
    max over each group of k consecutive channels. The reference declares the
    type (layer.h kMaxout) but never wires it into the factory; this is a
    real implementation. Config key: ``ngroup`` = number of output groups."""

    type_names = ("maxout",)

    def infer_shapes(self, in_shapes: List[Shape4]) -> List[Shape4]:
        assert len(in_shapes) == 1, "maxout: 1-1 connection only"
        n, c, h, w = in_shapes[0]
        k = self.param.num_group
        assert k > 1 and c % k == 0, "maxout: ngroup must divide channels"
        return [(n, c // k, h, w)]

    def forward(self, params, buffers, inputs, ctx):
        self.check_n_inputs(inputs, 1)
        x = inputs[0]
        n, c, h, w = x.shape
        k = self.param.num_group
        return [x.reshape(n, c // k, k, h, w).max(axis=2)], buffers


class EltSumLayer(Layer):
    """N -> 1 elementwise sum of same-shape nodes (residual connections).

    No reference counterpart (the reference predates residual nets); the
    graph syntax already supports it: ``layer[a,b->c] = eltsum``.
    """

    type_names = ("eltsum",)

    def infer_shapes(self, in_shapes: List[Shape4]) -> List[Shape4]:
        assert len(in_shapes) >= 2, "eltsum: needs at least 2 inputs"
        for s in in_shapes[1:]:
            assert s == in_shapes[0], \
                f"eltsum: input shapes differ: {s} vs {in_shapes[0]}"
        return [in_shapes[0]]

    def forward(self, params, buffers, inputs, ctx):
        assert len(inputs) >= 2, "eltsum: needs at least 2 inputs"
        out = inputs[0]
        for x in inputs[1:]:
            out = out + x
        return [out], buffers
