"""PairTest layer: differential testing of two layer implementations.

Reference: ``src/layer/pairtest_layer-inl.hpp`` — config
``layer[..] = pairtest-<master>-<slave>`` runs both layers on the same inputs
each step and reports when they diverge (relative abs error > 1e-5, :194).
The reference compares four things, all reproduced here:

* forward outputs (``CmpResult(..., "Forward")``, :89-93)
* propagated input gradients (``Backprop`` nodes_in compare, :110-117)
* weight gradients after backprop (``Cmp("After-Backprop:grad")``, :108)
* weights before each forward (``Cmp("Before-Forward:weight")``, :78) —
  master and slave are updated by the optimizer from their *own* gradients
  (``ApplyVisitor`` visits both sides, :122-125), so weight drift is the
  integrated signal that gradients ever differed.

Mechanics in the traced-step world: the master's outputs drive the graph.
The slave sees the same input *values* but a ``stop_gradient`` on them, and
its outputs join the master's through a straight-through term
``m + (s - stop_gradient(s))`` — numerically exactly ``m``, but handing the
slave's parameters the identical upstream cotangent the master receives, so
both sides' weight-grads are real and the updater updates both (reference
behavior).  Input-gradient and weight-gradient comparison runs inside the
traced forward via a probe-cotangent ``jax.vjp`` of both sides; all
comparison results are recorded in the step's diagnostics dict (returned by
the jitted step, so checking costs no host sync in the hot loop).  The
host-side harness form of the same comparison is
:func:`cxxnet_tpu.testing.diff_layers`.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List

import jax
import jax.numpy as jnp
from jax import lax

from .base import ForwardContext, Layer, Params, Shape4

PAIRTEST_RTOL = 1e-5  # reference threshold, pairtest_layer-inl.hpp:194


def relative_error(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    a = a.astype(jnp.float32)
    b = b.astype(jnp.float32)
    denom = jnp.maximum(jnp.abs(a), jnp.abs(b))
    err = jnp.abs(a - b) / jnp.maximum(denom, 1e-20)
    err = jnp.where(denom < 1e-20, 0.0, err)
    # NaN anywhere is an automatic failure (reference checks NaN too)
    return jnp.where(jnp.isnan(a) | jnp.isnan(b), jnp.inf, err).max()


def tree_relative_error(a, b) -> jnp.ndarray:
    """Max relative error over matching leaves of two pytrees."""
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    if not la:
        return jnp.float32(0.0)
    return jnp.stack([relative_error(x, y) for x, y in zip(la, lb)]).max()


def sum_losses(ctx: ForwardContext) -> jnp.ndarray:
    return (sum(ctx.losses[1:], ctx.losses[0]) if ctx.losses
            else jnp.float32(0.0))


def probe_vjp_compare(master, slave, mp, sp, mb, sb, inputs, make_ctx,
                      probe_key):
    """Shared core of the After-Backprop comparisons
    (pairtest_layer-inl.hpp:95-118), used by both the in-graph PairTestLayer
    and the host-side :func:`cxxnet_tpu.testing.diff_layers`.

    Runs master and slave forward + reverse under ONE probe cotangent (plus
    the real loss cotangent for loss layers) and returns
    ``(m_out, s_out, m_loss, s_loss, in_grad_rel_err, wgrad_rel_err)``.
    ``make_ctx`` must build a fresh ForwardContext with identical rng state
    on every call so both sides draw the same randomness.
    """
    def run(layer, bufs):
        def f(p, xs):
            c = make_ctx()
            outs, _ = layer.forward(p, bufs, xs, c)
            return [o.astype(jnp.float32) for o in outs], sum_losses(c)
        return f

    (m_o, m_loss), vjp_m = jax.vjp(run(master, mb), mp, inputs)
    (s_o, s_loss), vjp_s = jax.vjp(run(slave, sb), sp, inputs)
    probes = [jax.random.normal(jax.random.fold_in(probe_key, 7331 + i),
                                o.shape, jnp.float32)
              for i, o in enumerate(m_o)] if probe_key is not None else \
             [jnp.ones(o.shape, jnp.float32) for o in m_o]
    cot = (probes, jnp.float32(1.0))
    dwm, dxm = vjp_m(cot)
    dws, dxs = vjp_s(cot)
    in_err = jnp.stack([relative_error(a, b)
                        for a, b in zip(dxm, dxs)]).max()
    w_err = tree_relative_error(dwm, dws) \
        if jax.tree.leaves(dwm) else jnp.float32(0.0)
    return m_o, s_o, m_loss, s_loss, in_err, w_err


class PairTestLayer(Layer):
    type_names = ("pairtest",)

    def __init__(self, master: Layer, slave: Layer):
        super().__init__()
        self.master = master
        self.slave = slave

    @property
    def is_loss(self):
        return self.master.is_loss

    def set_param(self, name, val):
        # master:/slave: prefixed params route to one side (reference :127-136)
        if name.startswith("master:"):
            self.master.set_param(name[len("master:"):], val)
        elif name.startswith("slave:"):
            self.slave.set_param(name[len("slave:"):], val)
        else:
            self.master.set_param(name, val)
            self.slave.set_param(name, val)

    def infer_shapes(self, in_shapes: List[Shape4]) -> List[Shape4]:
        m = self.master.infer_shapes(in_shapes)
        s = self.slave.infer_shapes(in_shapes)
        assert m == s, f"pairtest: master/slave output shapes differ: {m} vs {s}"
        return m

    def init_params(self, key, in_shapes, dtype=jnp.float32):
        mp = self.master.init_params(key, in_shapes, dtype)
        # master -> slave weight sync at init (reference InitModel:137-141);
        # assumes both sides use the same param tags (true for the zoo).
        # Real copies, not aliases: both sides are donated to the jitted
        # step and an aliased buffer would be donated twice.
        return {"master": mp, "slave": jax.tree.map(jnp.array, mp)}

    def init_buffers(self, in_shapes):
        return {"master": self.master.init_buffers(in_shapes),
                "slave": self.slave.init_buffers(in_shapes)}

    def _child_ctx(self, ctx: ForwardContext, rng_count: int) -> ForwardContext:
        """Fresh losses/diagnostics, shared rng stream reset to rng_count so
        master and slave draw identical randomness (dropout masks etc.)."""
        return dataclasses.replace(ctx, losses=[], diagnostics={},
                                   _rng_count=rng_count)

    def forward(self, params, buffers, inputs, ctx):
        mp = params.get("master", {})
        sp = params.get("slave", {})
        mb = buffers.get("master", {})
        sb = buffers.get("slave", {})
        base_count = ctx._rng_count
        tag = self.name or (f"pairtest-{self.master.type_names[0]}"
                            f"-{self.slave.type_names[0]}")
        diag: Dict[str, jnp.ndarray] = ctx.diagnostics

        # Before-Forward:weight — drift of optimizer-updated weights (:78)
        if mp and sp:
            diag[f"{tag}:weight_rel_err"] = tree_relative_error(mp, sp)

        mctx = self._child_ctx(ctx, base_count)
        m_out, m_buf = self.master.forward(mp, mb, inputs, mctx)
        sctx = self._child_ctx(ctx, base_count)
        s_in = [lax.stop_gradient(x) for x in inputs]
        s_out, s_buf = self.slave.forward(sp, sb, s_in, sctx)
        # master drives the graph: its losses/rng-consumption propagate;
        # the slave's loss terms are measured but NOT trained on
        ctx.losses.extend(mctx.losses)
        ctx.diagnostics.update(mctx.diagnostics)
        ctx._rng_count = mctx._rng_count

        diag[f"{tag}:fwd_rel_err"] = jnp.stack(
            [relative_error(a, b) for a, b in zip(m_out, s_out)]).max()
        if mctx.losses or sctx.losses:
            diag[f"{tag}:loss_rel_err"] = relative_error(
                sum_losses(mctx), sum_losses(sctx))

        if ctx.train:
            self._compare_grads(mp, sp, mb, sb, list(inputs), ctx,
                                base_count, m_out, tag)

        # straight-through: value is exactly m, cotangent reaches the slave's
        # params so its weight grads are real (reference ApplyVisitor both).
        # Non-finite slave outputs are zeroed out of the residual — a broken
        # slave must be *reported* (diagnostics above), not allowed to NaN
        # the master-driven graph.
        def st(s):
            return jnp.where(jnp.isfinite(s), s, 0.0).astype(s.dtype)
        outs = [m + (st(s) - lax.stop_gradient(st(s)))
                for m, s in zip(m_out, s_out)]
        return outs, {"master": m_buf, "slave": s_buf}

    def _compare_grads(self, mp, sp, mb, sb, inputs, ctx, base_count,
                       m_out, tag):
        """After-Backprop comparisons (:95-118): input grads + weight grads
        of both sides under an identical probe cotangent (and the real loss
        cotangent for loss layers).

        The computation is fenced behind a custom_vjp with zero cotangents:
        its results are pure diagnostics, and fencing keeps the train step's
        outer autodiff from trying to linearize the inner ``jax.vjp``
        (impossible for callback-backed slaves like the torch adapter)."""

        def compute(mp, sp, mb, sb, inputs, rng):
            _, _, _, _, in_err, w_err = probe_vjp_compare(
                self.master, self.slave, mp, sp, mb, sb, inputs,
                lambda: self._child_ctx(ctx, base_count), rng)
            return in_err, w_err

        fenced = jax.custom_jvp(compute)

        @fenced.defjvp
        def _zero_jvp(primals, tangents):  # noqa: ANN001
            out = compute(*primals)
            return out, jax.tree.map(jnp.zeros_like, out)

        in_err, w_err = fenced(mp, sp, mb, sb, inputs, ctx.rng)
        ctx.diagnostics[f"{tag}:in_grad_rel_err"] = in_err
        ctx.diagnostics[f"{tag}:wgrad_rel_err"] = w_err
