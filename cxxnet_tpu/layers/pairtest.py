"""PairTest layer: differential testing of two layer implementations.

Reference: ``src/layer/pairtest_layer-inl.hpp`` — config
``layer[..] = pairtest-<master>-<slave>`` runs both layers on the same inputs
each step and reports when outputs/gradients diverge (relative abs error >
1e-5, :194).  Here the master's outputs drive the graph; the slave runs on
the same inputs with master-synced parameters and the max relative error is
recorded into the step's diagnostics dict (returned by the jitted step, so
checking is free of host sync in the hot loop).  Full gradient-level
comparison lives in :mod:`cxxnet_tpu.testing` (``diff_layers``), which is the
idiomatic jax form of the reference's weight-grad visitor comparison.
"""

from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp

from .base import ForwardContext, Layer, Params, Shape4

PAIRTEST_RTOL = 1e-5  # reference threshold, pairtest_layer-inl.hpp:194


def relative_error(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    a = a.astype(jnp.float32)
    b = b.astype(jnp.float32)
    denom = jnp.maximum(jnp.abs(a), jnp.abs(b))
    err = jnp.abs(a - b) / jnp.maximum(denom, 1e-20)
    err = jnp.where(denom < 1e-20, 0.0, err)
    # NaN anywhere is an automatic failure (reference checks NaN too)
    return jnp.where(jnp.isnan(a) | jnp.isnan(b), jnp.inf, err).max()


class PairTestLayer(Layer):
    type_names = ("pairtest",)

    def __init__(self, master: Layer, slave: Layer):
        super().__init__()
        self.master = master
        self.slave = slave

    @property
    def is_loss(self):
        return self.master.is_loss

    def set_param(self, name, val):
        # master:/slave: prefixed params route to one side (reference :127-136)
        if name.startswith("master:"):
            self.master.set_param(name[len("master:"):], val)
        elif name.startswith("slave:"):
            self.slave.set_param(name[len("slave:"):], val)
        else:
            self.master.set_param(name, val)
            self.slave.set_param(name, val)

    def infer_shapes(self, in_shapes: List[Shape4]) -> List[Shape4]:
        m = self.master.infer_shapes(in_shapes)
        s = self.slave.infer_shapes(in_shapes)
        assert m == s, f"pairtest: master/slave output shapes differ: {m} vs {s}"
        return m

    def init_params(self, key, in_shapes, dtype=jnp.float32):
        mp = self.master.init_params(key, in_shapes, dtype)
        # master -> slave weight sync at init (reference InitModel:137-141);
        # assumes both sides use the same param tags (true for the zoo).
        return {"master": mp, "slave": jax.tree.map(lambda x: x, mp)}

    def init_buffers(self, in_shapes):
        return {"master": self.master.init_buffers(in_shapes),
                "slave": self.slave.init_buffers(in_shapes)}

    def forward(self, params, buffers, inputs, ctx):
        m_out, m_buf = self.master.forward(
            params.get("master", {}), buffers.get("master", {}), inputs, ctx)
        s_out, s_buf = self.slave.forward(
            params.get("slave", {}), buffers.get("slave", {}), inputs, ctx)
        err = jnp.stack([relative_error(a, b)
                         for a, b in zip(m_out, s_out)]).max()
        tag = self.name or f"pairtest-{self.master.type_names[0]}-{self.slave.type_names[0]}"
        ctx.diagnostics[f"{tag}:fwd_rel_err"] = err
        return m_out, {"master": m_buf, "slave": s_buf}
