"""Fully-connected layers: fullc and fixconn.

Reference: ``src/layer/fullc_layer-inl.hpp`` (out = in · Wᵀ + bias, weight
shape (nhidden, nin)) and ``fixconn_layer-inl.hpp`` (fixed sparse projection
loaded from a text file).  These are the pure-GEMM path — on TPU they map
straight onto the MXU.
"""

from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp
import numpy as np

from ..analysis.schema import K
from .base import ForwardContext, Layer, Params, Shape4, as_mat


class FullConnectLayer(Layer):
    """out = in · Wᵀ + bias. Weight tagged "wmat" (nhidden, nin), bias "bias"."""

    type_names = ("fullc",)

    @staticmethod
    def model_shard_spec(tag: str, shape, model_size: int):
        """Sharding policy for a ``model`` mesh axis (the trainer's
        ``_make_shardings`` consults the layer so the policy lives next
        to the math it shards): the big GEMM weight splits its output
        rows over ``model`` — the ``fullc_gather`` tensor-parallel mode,
        where GSPMD inserts the activation all-gathers, and the
        dp_overlap path gathers the weight shards at segment entry.
        Returns a PartitionSpec or None (replicate)."""
        from jax.sharding import PartitionSpec as P
        if tag == "wmat" and len(shape) == 2 \
                and shape[0] % model_size == 0:
            return P("model", None)
        return None

    def infer_shapes(self, in_shapes: List[Shape4]) -> List[Shape4]:
        assert len(in_shapes) == 1, "fullc: 1-1 connection only"
        n, c, h, w = in_shapes[0]
        assert c == 1 and h == 1, "fullc: input must be a flat (n,1,1,d) node"
        assert self.param.num_hidden > 0, "fullc: must set nhidden"
        return [(n, 1, 1, self.param.num_hidden)]

    def init_params(self, key, in_shapes, dtype=jnp.float32):
        n, c, h, w = in_shapes[0]
        nhidden = self.param.num_hidden
        kw, kb = jax.random.split(key)
        wmat = self.param.rand_init_weight(kw, (nhidden, w), w, nhidden, dtype)
        params = {"wmat": wmat}
        if not self.param.no_bias:
            params["bias"] = jnp.full((nhidden,), self.param.init_bias, dtype)
        return params

    def forward(self, params, buffers, inputs, ctx):
        self.check_n_inputs(inputs, 1)
        x = as_mat(inputs[0])
        w = params["wmat"].astype(x.dtype)
        out = jnp.dot(x, w.T)
        if "bias" in params:
            out = out + params["bias"].astype(x.dtype)[None, :]
        return [out.reshape(out.shape[0], 1, 1, out.shape[1])], buffers


class FixConnectLayer(Layer):
    """Fixed (non-learned) sparse projection (fixconn_layer-inl.hpp:13-93).

    The sparse matrix text format is: header "nrow ncol nnz" then nnz lines of
    "row col value"; stored densely as a non-trainable buffer.
    """

    type_names = ("fixconn",)
    extra_config_keys = (
        K("fixconn_weight", "path",
          help="sparse projection table file"),
    )

    def __init__(self):
        super().__init__()
        self.fname_weight = "NULL"

    def set_param(self, name, val):
        if name == "fixconn_weight":
            self.fname_weight = val
        else:
            super().set_param(name, val)

    def infer_shapes(self, in_shapes: List[Shape4]) -> List[Shape4]:
        assert len(in_shapes) == 1, "fixconn: 1-1 connection only"
        n, c, h, w = in_shapes[0]
        assert c == 1 and h == 1, "fixconn: input must be a flat node"
        assert self.param.num_hidden > 0, "fixconn: must set nhidden"
        return [(n, 1, 1, self.param.num_hidden)]

    def init_buffers(self, in_shapes: List[Shape4]) -> Params:
        n, c, h, w = in_shapes[0]
        assert self.fname_weight != "NULL", "fixconn: must set fixconn_weight"
        dense = np.zeros((self.param.num_hidden, w), np.float32)
        with open(self.fname_weight) as f:
            toks = f.read().split()
        nrow, ncol, nnz = int(toks[0]), int(toks[1]), int(toks[2])
        assert (nrow, ncol) == dense.shape, \
            f"fixconn: weight shape {(nrow, ncol)} != architecture {dense.shape}"
        vals = toks[3:]
        assert len(vals) == 3 * nnz, "fixconn: invalid sparse matrix format"
        for k in range(nnz):
            r, cc, v = int(vals[3 * k]), int(vals[3 * k + 1]), float(vals[3 * k + 2])
            dense[r, cc] = v
        return {"wmat": jnp.asarray(dense)}

    def forward(self, params, buffers, inputs, ctx):
        self.check_n_inputs(inputs, 1)
        x = as_mat(inputs[0])
        w = jax.lax.stop_gradient(buffers["wmat"]).astype(x.dtype)
        out = jnp.dot(x, w.T)
        return [out.reshape(out.shape[0], 1, 1, out.shape[1])], buffers
