"""Loss layers: softmax, l2_loss, multi_logistic.

Reference: ``src/layer/loss/*``.  Loss layers are self-loops: forward applies
the output transform (softmax / identity / sigmoid) and, at training time,
contributes a scalar loss term whose jax gradient reproduces the reference's
hand-set gradient ``(p - y) * grad_scale / (batch_size * update_period)``
(loss_layer_base-inl.hpp:59-62).  The reference computes that gradient on the
CPU with a D2H2D round trip per step (:87-96); here the loss lives inside the
jitted step, fully on-device — the "host callback slot" the survey mentions is
unnecessary because the gradient is exact.
"""

from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp

from ..analysis.schema import K
from ..ops import nn as N
from .base import ForwardContext, Layer, Params, Shape4, as_mat


class LossLayerBase(Layer):
    is_loss = True
    extra_config_keys = (
        K("target", "str", help="label field this loss consumes"),
        K("grad_scale", "float"),
    )

    def __init__(self):
        super().__init__()
        self.target = "label"
        self.grad_scale = 1.0

    def set_param(self, name, val):
        if name == "target":
            self.target = val
        elif name == "grad_scale":
            self.grad_scale = float(val)
        else:
            super().set_param(name, val)

    def infer_shapes(self, in_shapes: List[Shape4]) -> List[Shape4]:
        assert len(in_shapes) == 1, "loss layer: self-loop connection only"
        return [in_shapes[0]]

    def _transform(self, x2d: jnp.ndarray) -> jnp.ndarray:
        return x2d

    def _per_instance_loss(self, x2d: jnp.ndarray, out2d: jnp.ndarray,
                           labels: jnp.ndarray) -> jnp.ndarray:
        """Return per-instance loss vector (batch,). ``x2d`` is the pre-
        transform input, ``out2d`` the transformed output."""
        raise NotImplementedError

    def forward(self, params, buffers, inputs, ctx):
        self.check_n_inputs(inputs, 1)
        x = inputs[0]
        x2d = as_mat(x)
        out2d = self._transform(x2d)
        if ctx.labels is not None and ctx.train:
            y = ctx.labels.get(self.target)
            per_inst = self._per_instance_loss(x2d, out2d, y)
            if ctx.labels.mask is not None:
                # tail-batch replica padding contributes zero loss (and
                # therefore zero gradient); see DataBatch.tail_mask_padd
                per_inst = per_inst * ctx.labels.mask.astype(per_inst.dtype)
            # loss_scale = grad_scale / (batch_size * update_period); the sum
            # over instances then yields exactly the reference per-instance
            # gradient scaling (loss_layer_base-inl.hpp:61-62).
            ctx.losses.append(per_inst.sum() * (self.grad_scale * ctx.loss_scale))
        return [out2d.reshape(x.shape)], buffers


class SoftmaxLayer(LossLayerBase):
    """Softmax transform + cross-entropy on integer class labels
    (loss/softmax_layer-inl.hpp: forward = mshadow::Softmax, grad = p, with
    p[y] -= 1)."""

    type_names = ("softmax",)

    def _transform(self, x2d):
        return N.softmax(x2d)

    def _per_instance_loss(self, x2d, out2d, labels):
        logp = N.log_softmax(x2d.astype(jnp.float32))
        idx = labels[:, 0].astype(jnp.int32)
        return -jnp.take_along_axis(logp, idx[:, None], axis=1)[:, 0]


class L2LossLayer(LossLayerBase):
    """Identity transform + squared error: grad = p - y ⇒ loss = ½‖p − y‖²
    (loss/l2_loss_layer-inl.hpp:23-32)."""

    type_names = ("l2_loss",)

    def _per_instance_loss(self, x2d, out2d, labels):
        d = out2d.astype(jnp.float32) - labels.astype(jnp.float32)
        return 0.5 * jnp.square(d).sum(axis=1)


class MultiLogisticLayer(LossLayerBase):
    """Elementwise sigmoid + binary cross-entropy: grad = σ(x) - y
    (loss/multi_logistic_layer-inl.hpp:19-32)."""

    type_names = ("multi_logistic",)

    def _transform(self, x2d):
        return jax.nn.sigmoid(x2d)

    def _per_instance_loss(self, x2d, out2d, labels):
        x = x2d.astype(jnp.float32)
        y = labels.astype(jnp.float32)
        # numerically stable BCE-with-logits whose grad wrt x is sigmoid(x)-y
        per_elem = jnp.maximum(x, 0) - x * y + jnp.log1p(jnp.exp(-jnp.abs(x)))
        return per_elem.sum(axis=1)
