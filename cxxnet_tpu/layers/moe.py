"""Mixture-of-experts layer with expert-parallel sharding.

No reference counterpart (the reference predates MoE; SURVEY.md §5.7 treats
long-context/scale substrates as design obligations of this framework).
Switch-transformer-style top-1 routing with fixed expert capacity: shapes
stay static under jit, and on a mesh with an ``expert`` axis the per-expert
FFN weights shard over it — GSPMD turns the dispatch/combine einsums into
all-to-alls over ICI, which IS expert parallelism.

Config::

    layer[+1] = moe
      num_expert = 8
      nhidden = 2048            # expert FFN width
      capacity_factor = 1.25    # per-expert slots = cf * tokens / E
      moe_alpha = 0.01          # load-balance aux loss weight

Forward (tokens t = batch*seq, model dim d, experts e, capacity c):
  gate probs (t, e) -> top-1 expert + position-in-expert via cumsum;
  dispatch  x_e = einsum('tec,td->ecd', D, x)      (all-to-all on e)
  expert FFN x_e @ w1[e] -> gelu -> @ w2[e]        (batched per-expert MXU)
  combine   y  = einsum('ecd,tec->td', y_e, D * p) (all-to-all back)
Tokens beyond an expert's capacity are dropped (standard Switch behavior:
their residual path carries them).  The Switch load-balancing aux loss
alpha * E * sum_e f_e * P_e is appended to ctx.losses.
"""

from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .base import ForwardContext, Layer, Shape4


def _expert_mesh(ctx: ForwardContext):
    mesh = getattr(ctx, "mesh", None)
    if mesh is not None and "expert" in mesh.axis_names \
            and mesh.shape["expert"] > 1:
        return mesh
    return None


class MoELayer(Layer):
    type_names = ("moe",)

    def __init__(self):
        super().__init__()
        self.num_expert = 0
        self.capacity_factor = 1.25
        self.moe_alpha = 0.01

    def set_param(self, name, val):
        if name == "num_expert":
            self.num_expert = int(val)
        elif name == "capacity_factor":
            self.capacity_factor = float(val)
        elif name == "moe_alpha":
            self.moe_alpha = float(val)
        else:
            super().set_param(name, val)

    def infer_shapes(self, in_shapes: List[Shape4]) -> List[Shape4]:
        assert len(in_shapes) == 1, "moe: 1-1 connection only"
        assert self.num_expert > 1, "moe: set num_expert"
        assert self.param.num_hidden > 0, "moe: set nhidden (FFN width)"
        return [in_shapes[0]]

    def _capacity(self, tokens: int) -> int:
        return max(1, int(self.capacity_factor * tokens / self.num_expert))

    def init_params(self, key, in_shapes, dtype=jnp.float32):
        d = in_shapes[0][3]
        e, h = self.num_expert, self.param.num_hidden
        ks = jax.random.split(key, 3)
        p = self.param
        return {
            "gate": p.rand_init_weight(ks[0], (d, e), d, e, dtype),
            "wmat": p.rand_init_weight(ks[1], (e, d, h), d, h, dtype),
            "wmat2": p.rand_init_weight(ks[2], (e, h, d), h, d, dtype),
            "bias": jnp.full((e, h), p.init_bias, dtype),
            "bias2": jnp.full((e, d), p.init_bias, dtype),
        }

    def forward(self, params, buffers, inputs, ctx):
        self.check_n_inputs(inputs, 1)
        x4 = inputs[0]                       # (b, 1, s, d)
        b, _, s, d = x4.shape
        e = self.num_expert
        t = b * s
        c = self._capacity(t)
        x = x4.reshape(t, d)

        # top-1 routing in f32 (gate numerics should not depend on dtype)
        logits = x.astype(jnp.float32) @ params["gate"].astype(jnp.float32)
        probs = jax.nn.softmax(logits, axis=-1)          # (t, e)
        expert = jnp.argmax(probs, axis=-1)              # (t,)
        onehot = jax.nn.one_hot(expert, e, dtype=jnp.float32)
        gate_p = jnp.sum(probs * onehot, axis=-1)        # (t,)

        # position of each token within its expert; beyond-capacity drops
        pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot  # (t, e)
        pos_tok = jnp.sum(pos, axis=-1)                    # (t,)
        keep = pos_tok < c
        disp = onehot * keep[:, None]                    # (t, e)
        slot = jax.nn.one_hot(pos_tok.astype(jnp.int32), c,
                              dtype=jnp.float32)              # (t, c)
        dmat = disp[:, :, None] * slot[:, None, :]       # (t, e, c)
        dmat = dmat.astype(x.dtype)

        mesh = _expert_mesh(ctx)

        def eshard(a, spec):
            if mesh is None:
                return a
            return jax.lax.with_sharding_constraint(
                a, NamedSharding(mesh, spec))

        # dispatch: (t, e, c) x (t, d) -> (e, c, d); sharding the e axis
        # makes GSPMD emit the all-to-all over the expert mesh axis
        xe = jnp.einsum("tec,td->ecd", dmat, x)
        xe = eshard(xe, P("expert", None, None))
        w1 = eshard(params["wmat"].astype(x.dtype), P("expert", None, None))
        w2 = eshard(params["wmat2"].astype(x.dtype), P("expert", None, None))
        b1 = eshard(params["bias"].astype(x.dtype), P("expert", None))
        b2 = eshard(params["bias2"].astype(x.dtype), P("expert", None))
        h = jax.nn.gelu(jnp.einsum("ecd,edh->ech", xe, w1)
                        + b1[:, None, :])
        ye = jnp.einsum("ech,ehd->ecd", h, w2) + b2[:, None, :]
        ye = eshard(ye, P("expert", None, None))
        # combine, weighted by the gate probability (straight-through on
        # the routing, differentiable through the prob)
        comb = dmat * gate_p.astype(x.dtype)[:, None, None]
        y = jnp.einsum("ecd,tec->td", ye, comb)
        # dropped tokens ride the residual
        y = y + jnp.where(keep[:, None], jnp.zeros((), x.dtype), x)

        if ctx.train and self.moe_alpha > 0:
            # Switch aux loss: E * sum_e (fraction routed)*(mean prob) —
            # already a batch statistic, so scale by loss_scale*b
            # (= 1/update_period): its weight must stay O(moe_alpha)
            # regardless of sequence length
            frac = jnp.mean(onehot, axis=0)
            meanp = jnp.mean(probs, axis=0)
            ctx.losses.append(
                (self.moe_alpha * e * jnp.sum(frac * meanp)
                 ).astype(jnp.float32) * ctx.loss_scale * b)
        return [y.reshape(b, 1, s, d)], buffers
