"""Mixture-of-experts layer with expert-parallel sharding.

No reference counterpart (the reference predates MoE; SURVEY.md §5.7 treats
long-context/scale substrates as design obligations of this framework).
Switch-transformer-style top-1 routing with fixed expert capacity: shapes
stay static under jit, and on a mesh with an ``expert`` axis the per-expert
FFN weights shard over it — GSPMD turns the dispatch/combine einsums into
all-to-alls over ICI, which IS expert parallelism.

Config::

    layer[+1] = moe
      num_expert = 8
      nhidden = 2048            # expert FFN width
      capacity_factor = 1.25    # per-expert slots = cf * tokens / E
      moe_alpha = 0.01          # load-balance aux loss weight

Forward (tokens t = batch*seq, model dim d, experts e, capacity c):
  gate probs (t, e) -> top-1 expert + position-in-expert;
  dispatch  x_e (e, c, d); expert FFN x_e @ w1[e] -> gelu -> @ w2[e];
  combine   y = x + gate_p * FFN(x)  (dropped tokens: y = x — the residual
  applies to EVERY token, so behavior is continuous at the capacity
  boundary rather than flipping between gate_p*E(x) and x).

Two dispatch implementations behind one contract (``moe_dispatch``):

* ``dense`` — the one-hot (t, e, c) einsum pair.  O(t*e*c) mask FLOPs and
  an e*c*t intermediate: exact, simple, and on an ``expert`` mesh axis
  GSPMD turns the einsums into all-to-alls — kept as the small-scale
  oracle and the expert-parallel path.
* ``sorted`` (default off-mesh) — argsort tokens by expert, derive each
  token's slot from its position past its expert's segment start, then
  move data with two gathers (slot->token for dispatch, token->slot for
  combine).  The only scatters are int32 index builds of size e*c and t.
  No (t, e, c) tensor ever exists: memory O(e*c*d + t) and the mask
  arithmetic drops from O(t*e*c) to O(t log t) for the sort.

``auto`` picks dense on an expert mesh, sorted otherwise.  The Switch
load-balancing aux loss alpha * E * sum_e f_e * P_e is appended to
ctx.losses (tail-batch replica tokens are excluded via the loss mask).
"""

from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..analysis.schema import K
from .base import ForwardContext, Layer, Shape4


def expert_host_axis(mesh) -> str | None:
    """The mesh axis that hosts the per-expert dimension, or ``None``.
    A dedicated ``expert`` axis wins; otherwise the ``model`` axis hosts
    the experts (``mesh = data:N,model:M`` is the first-class multi-axis
    config — expert weights shard over ``model`` at rest via
    NamedSharding, and the dispatch/combine einsums become GSPMD
    all-to-alls over it exactly as they would over ``expert``).  The
    single source of truth for both the trainer's rest shardings
    (``_make_shardings``) and the runtime constraints below."""
    if mesh is not None:
        for ax in ("expert", "model"):
            if ax in mesh.axis_names and mesh.shape[ax] > 1:
                return ax
    return None


def _expert_axis(ctx: ForwardContext):
    """``(mesh, axis)`` for this forward, or ``(None, None)``."""
    mesh = getattr(ctx, "mesh", None)
    ax = expert_host_axis(mesh)
    return (mesh, ax) if ax is not None else (None, None)


class MoELayer(Layer):
    type_names = ("moe",)

    @staticmethod
    def shard_spec(tag: str, shape, axis: str, size: int):
        """Rest sharding over mesh axis ``axis`` (``expert``, or
        ``model`` when no expert axis exists — see :func:`_expert_axis`):
        every per-expert tensor splits its leading (expert) dim; the
        gate stays replicated (every token scores every expert).
        Returns a PartitionSpec or None (replicate)."""
        from jax.sharding import PartitionSpec as P
        if tag != "gate" and len(shape) >= 1 and shape[0] % size == 0:
            return P(axis, *([None] * (len(shape) - 1)))
        return None
    extra_config_keys = (
        K("num_expert", "int", lo=2),
        K("capacity_factor", "float", lo=0.0),
        K("moe_alpha", "float"),
        K("moe_dispatch", "enum", choices=("auto", "dense", "sorted")),
        K("router_jitter", "float", lo=0.0),
    )

    def __init__(self):
        super().__init__()
        self.num_expert = 0
        self.capacity_factor = 1.25
        self.moe_alpha = 0.01
        self.moe_dispatch = "auto"   # auto | dense | sorted
        self.router_jitter = 0.0     # train-time multiplicative gate noise

    def set_param(self, name, val):
        if name == "num_expert":
            self.num_expert = int(val)
        elif name == "capacity_factor":
            self.capacity_factor = float(val)
        elif name == "moe_alpha":
            self.moe_alpha = float(val)
        elif name == "moe_dispatch":
            assert val in ("auto", "dense", "sorted"), \
                f"moe_dispatch must be auto|dense|sorted, got {val!r}"
            self.moe_dispatch = val
        elif name == "router_jitter":
            self.router_jitter = float(val)
        else:
            super().set_param(name, val)

    def infer_shapes(self, in_shapes: List[Shape4]) -> List[Shape4]:
        assert len(in_shapes) == 1, "moe: 1-1 connection only"
        assert self.num_expert > 1, "moe: set num_expert"
        assert self.param.num_hidden > 0, "moe: set nhidden (FFN width)"
        return [in_shapes[0]]

    def _capacity(self, tokens: int) -> int:
        return max(1, int(self.capacity_factor * tokens / self.num_expert))

    def init_params(self, key, in_shapes, dtype=jnp.float32):
        d = in_shapes[0][3]
        e, h = self.num_expert, self.param.num_hidden
        ks = jax.random.split(key, 3)
        p = self.param
        return {
            "gate": p.rand_init_weight(ks[0], (d, e), d, e, dtype),
            "wmat": p.rand_init_weight(ks[1], (e, d, h), d, h, dtype),
            "wmat2": p.rand_init_weight(ks[2], (e, h, d), h, d, dtype),
            "bias": jnp.full((e, h), p.init_bias, dtype),
            "bias2": jnp.full((e, d), p.init_bias, dtype),
        }

    # -- dispatch/combine implementations ---------------------------------
    def _ffn(self, params, xe, eshard):
        """Batched per-expert FFN on (e, c, d) slots."""
        w1 = eshard(params["wmat"].astype(xe.dtype), P("expert", None, None))
        w2 = eshard(params["wmat2"].astype(xe.dtype),
                    P("expert", None, None))
        b1 = eshard(params["bias"].astype(xe.dtype), P("expert", None))
        b2 = eshard(params["bias2"].astype(xe.dtype), P("expert", None))
        h = jax.nn.gelu(jnp.einsum("ecd,edh->ech", xe, w1) + b1[:, None, :])
        return jnp.einsum("ech,ehd->ecd", h, w2) + b2[:, None, :]

    def _dense_path(self, params, x, expert, gate_p, c, eshard):
        """One-hot (t, e, c) dispatch — exact oracle; on an expert mesh
        the einsums become GSPMD all-to-alls."""
        e = self.num_expert
        onehot = jax.nn.one_hot(expert, e, dtype=jnp.float32)
        pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot
        pos_tok = jnp.sum(pos, axis=-1)
        keep = pos_tok < c
        disp = onehot * keep[:, None]
        slot = jax.nn.one_hot(pos_tok.astype(jnp.int32), c,
                              dtype=jnp.float32)
        dmat = (disp[:, :, None] * slot[:, None, :]).astype(x.dtype)
        xe = eshard(jnp.einsum("tec,td->ecd", dmat, x),
                    P("expert", None, None))
        ye = eshard(self._ffn(params, xe, eshard), P("expert", None, None))
        comb = dmat * gate_p.astype(x.dtype)[:, None, None]
        return jnp.einsum("ecd,tec->td", ye, comb)

    def _sorted_path(self, params, x, expert, gate_p, c, eshard):
        """Sort-based dispatch: no (t, e, c) tensor.  A stable argsort by
        expert gives each token's position past its expert's segment
        start; data moves via two gathers (and their scatter-add
        transposes in backward), with only int32 index builds scattered."""
        e = self.num_expert
        t, d = x.shape
        ec = e * c
        order = jnp.argsort(expert, stable=True)          # (t,)
        sorted_e = expert[order]
        seg_start = jnp.searchsorted(sorted_e, jnp.arange(e), side="left")
        pos_sorted = jnp.arange(t) - seg_start[sorted_e]
        keep_sorted = pos_sorted < c
        dest = sorted_e * c + pos_sorted                  # slot per token
        dest_ok = jnp.where(keep_sorted, dest, ec)        # ec = dropped
        # which token fills each slot (empty slots stay at sentinel 0 and
        # are zero-masked after the gather)
        token_for_slot = jnp.zeros((ec,), jnp.int32).at[dest_ok].set(
            order.astype(jnp.int32), mode="drop")
        slot_filled = jnp.zeros((ec,), jnp.bool_).at[dest_ok].set(
            True, mode="drop")
        xe = jnp.where(slot_filled[:, None], x[token_for_slot],
                       jnp.zeros((), x.dtype)).reshape(e, c, d)
        ye = self._ffn(params, eshard(xe, P("expert", None, None)), eshard)
        # combine: token -> its slot (or sentinel ec for dropped)
        slot_of_token = jnp.full((t,), ec, jnp.int32).at[order].set(
            dest_ok.astype(jnp.int32))
        valid = slot_of_token < ec
        gathered = ye.reshape(ec, d)[jnp.minimum(slot_of_token, ec - 1)]
        return jnp.where(valid[:, None],
                         gathered * gate_p.astype(x.dtype)[:, None],
                         jnp.zeros((), x.dtype))

    def forward(self, params, buffers, inputs, ctx):
        self.check_n_inputs(inputs, 1)
        x4 = inputs[0]                       # (b, 1, s, d)
        b, _, s, d = x4.shape
        e = self.num_expert
        t = b * s
        c = self._capacity(t)
        x = x4.reshape(t, d)

        # top-1 routing in f32 (gate numerics should not depend on dtype)
        xg = x.astype(jnp.float32)
        if ctx.train and self.router_jitter > 0:
            eps = self.router_jitter
            xg = xg * jax.random.uniform(ctx.next_rng(), xg.shape,
                                         jnp.float32, 1 - eps, 1 + eps)
        logits = xg @ params["gate"].astype(jnp.float32)
        probs = jax.nn.softmax(logits, axis=-1)          # (t, e)
        expert = jnp.argmax(probs, axis=-1)              # (t,)
        gate_p = jnp.take_along_axis(probs, expert[:, None], axis=1)[:, 0]

        mesh, eaxis = _expert_axis(ctx)

        def eshard(a, spec):
            if mesh is None:
                return a
            # call sites spell the canonical "expert" axis; rewrite to
            # whichever axis actually hosts the experts on this mesh
            spec = P(eaxis, *tuple(spec)[1:])
            return jax.lax.with_sharding_constraint(
                a, NamedSharding(mesh, spec))

        dispatch = self.moe_dispatch
        if dispatch == "auto":
            # dense keeps the einsum structure GSPMD turns into expert
            # all-to-alls; sorted is the scalable single-host/dp default
            dispatch = "dense" if mesh is not None else "sorted"
        path = self._dense_path if dispatch == "dense" else self._sorted_path
        y = path(params, x, expert, gate_p, c, eshard)
        # EVERY token keeps its residual: y = x + gate_p * E(x), dropped
        # tokens y = x — continuous at the capacity boundary (round-2
        # advisor finding: the old form flipped between gate_p*E(x) and x)
        y = x + y

        if ctx.train and self.moe_alpha > 0:
            # Switch aux loss: E * sum_e (fraction routed)*(mean prob) —
            # already a batch statistic, so scale by loss_scale*b
            # (= 1/update_period): its weight must stay O(moe_alpha)
            # regardless of sequence length.  Tail-batch replica tokens
            # (loss mask 0) are excluded from both statistics.
            lmask = ctx.labels.mask if ctx.labels is not None else None
            onehot = jax.nn.one_hot(expert, e, dtype=jnp.float32)
            if lmask is not None:
                tm = jnp.repeat(lmask.astype(jnp.float32), s)  # (t,)
                denom = jnp.maximum(tm.sum(), 1.0)
                frac = (onehot * tm[:, None]).sum(axis=0) / denom
                meanp = (probs * tm[:, None]).sum(axis=0) / denom
            else:
                frac = jnp.mean(onehot, axis=0)
                meanp = jnp.mean(probs, axis=0)
            ctx.losses.append(
                (self.moe_alpha * e * jnp.sum(frac * meanp)
                 ).astype(jnp.float32) * ctx.loss_scale * b)
        return [y.reshape(b, 1, s, d)], buffers
