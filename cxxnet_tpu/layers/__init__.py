from .base import (ForwardContext, LabelInfo, Layer, LayerParam, Shape4,
                   as_mat, mat_shape)
from .registry import create_layer, layer_type_names, register

__all__ = ["ForwardContext", "LabelInfo", "Layer", "LayerParam", "Shape4",
           "as_mat", "mat_shape", "create_layer", "layer_type_names",
           "register"]
