"""Layer system core: functional, trace-friendly layers over 4-D nodes.

Design notes (vs the reference, ``src/layer/layer.h``):

* The reference's ``Node<xpu>`` is a mutable 4-D activation buffer
  (batch, channel, y, x) that layers write in place, and gradients reuse the
  same buffers (``layer.h:31-38,230-241``).  On TPU everything runs inside one
  traced, jitted step function, so nodes become *SSA values*: a layer's
  ``forward`` consumes input arrays and returns fresh output arrays, and
  autodiff is supplied by ``jax.grad`` over the whole step instead of
  hand-written ``Backprop`` methods.  Self-loop layers (dropout, bias, loss —
  ``nodes_in[0]==nodes_out[0]``) simply rebind the node's value.
* ``Connection`` (``layer.h:380-407``) survives as a thin record binding one
  layer instance to input/output node ids; per-connection scratch state
  (``ConnectState``) is unnecessary under tracing.
* Layer sharing (``kSharedLayer``, ``layer.h:283``) is expressed by pointing a
  connection at the primary connection's parameters.
* The weight-visitor mechanism (``visitor.h:26-165``) becomes ordinary pytree
  access: params are ``{layer_name: {tag: array}}`` with tags ``wmat``/``bias``
  exactly as the reference exposes them, so tag-scoped hyperparameters
  (``wmat:lr``) and GetWeight/SetWeight keep their semantics.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..analysis.schema import K, KeySpec

Shape4 = Tuple[int, int, int, int]  # (batch, channel, y, x)

# ``strict_config = 1`` (global key, default off): route config keys that
# every consumer silently drops through the lint reporter as warnings
# instead of losing them — the reference rule ("components ignore keys
# they don't know", doc/global.md) stays the default because globals are
# legitimately broadcast to every subsystem.
_STRICT_CONFIG = False


def set_strict_config(flag: bool) -> None:
    global _STRICT_CONFIG
    _STRICT_CONFIG = bool(flag)
    # fresh dedup window per toggle: a new net built under a new
    # strict_config=1 must warn again for the same (type, key)
    import sys
    conflint = sys.modules.get("cxxnet_tpu.analysis.conflint")
    if conflint is not None:
        conflint._reported.clear()


def strict_config_enabled() -> bool:
    return _STRICT_CONFIG


#: keys LayerParam.set_param consumes — shared by every layer; the common
#: hyperparameter surface of ``src/layer/param.h``
LAYER_PARAM_KEYS: Tuple[KeySpec, ...] = (
    K("init_sigma", "float", help="gaussian init stddev"),
    K("init_uniform", "float", help="uniform init bound (<=0 = xavier)"),
    K("init_bias", "float"),
    K("random_type", "enum",
      choices=("gaussian", "uniform", "xavier", "kaiming")),
    K("nhidden", "int", lo=1),
    K("nchannel", "int", lo=1),
    K("ngroup", "int", lo=1),
    K("kernel_size", "int", lo=1),
    K("kernel_height", "int", lo=1),
    K("kernel_width", "int", lo=1),
    K("stride", "int", lo=1),
    K("pad", "int", lo=0),
    K("pad_y", "int", lo=0),
    K("pad_x", "int", lo=0),
    K("no_bias", "int", lo=0, hi=1),
    K("silent", "int", lo=0, hi=1),
)


class ShapeError(ValueError):
    pass


def mat_shape(s: Shape4) -> Tuple[int, int]:
    """2-D (batch, c*h*w) view shape of a node (reference Node::mat())."""
    return (s[0], s[1] * s[2] * s[3])


class ChSegs:
    """Virtual channel concat (``concat_virtual = 1``): the value of a
    ``ch_concat`` node held as its branch segments instead of one
    materialized buffer.  Channelwise consumers (split, pools) operate
    per segment; a conv consumes it as a sum of K-sliced convs — so
    inception concats stop costing a full HBM copy forward and a
    slice-split backward.  Any unaware consumer materializes lazily
    (``materialize()``, cached).  Python-level only: never crosses a jit
    boundary; autodiff sees the underlying ops."""

    __slots__ = ("segs", "_mat")

    def __init__(self, segs):
        self.segs = list(segs)
        self._mat = None

    @property
    def shape(self):
        n, _, h, w = self.segs[0].shape
        return (n, sum(s.shape[1] for s in self.segs), h, w)

    def materialize(self):
        if self._mat is None:
            self._mat = jnp.concatenate(self.segs, axis=1)
        return self._mat


def materialize(x):
    return x.materialize() if isinstance(x, ChSegs) else x


def as_mat(x: jnp.ndarray) -> jnp.ndarray:
    x = materialize(x)
    return x.reshape(x.shape[0], -1)


#: chars jax.named_scope accepts; anything else in a user layer name is
#: replaced so config names can't break tracing or scope matching
_SCOPE_BAD = re.compile(r"[^A-Za-z0-9_.\-]")


def conn_scope_name(index: int, conn) -> str:
    """Canonical per-connection scope string: ``"<NN>-<name-or-type>"``.

    This is the SHARED contract between the three sides of layer
    attribution (doc/monitor.md "Layer attribution"): the net builder
    stamps each connection's forward with ``jax.named_scope`` under this
    string, the analytic cost model keys per-layer flops/bytes by it,
    and ``monitor/attribution.py`` matches it against profiler-trace op
    metadata.  The base comes from the connection's ``param_key``
    (``Network._layer_key``'s name-or-type resolution), so a
    ``layer_profile`` row and a monitor record like ``"16-fc6/wmat"``
    name the same layer the same way — modulo scope sanitization, since
    ``jax.named_scope`` rejects characters configs allow.  A SHARED
    connection reuses its primary's base under its OWN index (it
    executes separately even though parameters alias).  The zero-padded
    connection index makes scopes pairwise non-substring (no two
    connections share an index), so substring matching inside
    transform-wrapped paths like ``transpose(jvp(03-conv))`` is
    unambiguous."""
    base = conn.param_key.split("-", 1)[1]
    return f"{index:02d}-" + _SCOPE_BAD.sub("_", base)


@dataclasses.dataclass
class LabelInfo:
    """Labels routed to loss layers (reference ``layer.h:96-125``).

    ``fields`` maps a label-field name (from ``label_vec[a,b)`` config, default
    field name "label") to a (batch, label_width) float array.
    """

    fields: Dict[str, jnp.ndarray] = dataclasses.field(default_factory=dict)
    # 1.0 for real instances, 0.0 for round_batch padding (num_batch_padd).
    mask: Optional[jnp.ndarray] = None

    def get(self, name: str) -> jnp.ndarray:
        if name not in self.fields:
            raise KeyError(
                f"label field {name!r} not provided; available: {list(self.fields)}")
        return self.fields[name]


@dataclasses.dataclass
class DecodeState:
    """KV-cache plumbing for incremental decode (serve/decode.py).

    Threaded through :class:`ForwardContext` so cache-aware layers
    (embedding's position offset, attention's cache append + length-
    masked read) can see it without changing the ``forward`` signature.
    Two modes:

    * ``"prefill"`` — the forward runs over a whole prompt at its
      natural shape; attention layers CAPTURE their freshly computed
      (k, v) into ``caches[key]`` and otherwise compute the normal
      causal path, so prefill logits are byte-identical to a plain
      eval forward.
    * ``"step"`` — the forward runs one position (seq len 1) per row;
      attention layers SCATTER the new (k, v) into ``caches[key]`` at
      ``positions`` and attend over the whole cache under the mask
      ``arange(max_seqlen) <= positions``, which zeroes every not-yet-
      written slot exactly (softmax of ``NEG_INF`` underflows to 0.0),
      making the reduction bitwise equal to the full-forward one at f32.
    * ``"block"`` — the multi-column generalization of ``"step"``
      (speculative verify / chunked prefill, serve/decode.py): the
      forward runs ``W`` consecutive positions per row starting at
      ``positions``; attention layers scatter all ``W`` fresh (k, v)
      columns at ``positions + arange(W)`` (out-of-range columns drop)
      and query ``w`` attends under ``arange(max_seqlen) <= positions +
      w`` — causal within the block, length-masked against the cache —
      so each of the ``W`` logits rows is bitwise equal to the
      sequential ``"step"`` row at the same position.

    ``caches`` maps the attention connection's decode key (stamped by
    the engine) to ``{"k": (rows, heads, max_seqlen, head_dim),
    "v": ...}`` arrays; layers write updated arrays back in place of
    the old ones so the engine can return them as donated outputs.
    The cache arrays may be a narrower dtype than the activations
    (``decode_kv_dtype = bf16``): layers cast on write, and the score /
    p·V reductions accumulate in f32 as before.
    """

    mode: str                               # "prefill" | "step" | "block"
    caches: Dict[str, Dict[str, jnp.ndarray]]
    # (rows,) int32 — step/block mode: the (first) position being
    # written (= number of tokens already in the cache); prefill mode:
    # unused (None)
    positions: Optional[jnp.ndarray] = None
    max_seqlen: int = 0


@dataclasses.dataclass
class ForwardContext:
    """Per-call context threaded through the traced forward pass."""

    train: bool
    rng: Optional[jax.Array] = None
    labels: Optional[LabelInfo] = None
    # round counter for schedule-dependent layers (insanity annealing)
    epoch: Any = 0
    # gradient scaling for loss layers: grad_scale / (batch_size * update_period)
    loss_scale: float = 1.0
    # loss terms appended by loss layers during trace; summed by the trainer
    losses: List[jnp.ndarray] = dataclasses.field(default_factory=list)
    # diagnostics appended by pairtest layers etc.
    diagnostics: Dict[str, jnp.ndarray] = dataclasses.field(default_factory=dict)
    # device mesh for layers that shard explicitly (ring attention over a
    # "seq" axis); None for single-device runs
    mesh: Optional[Any] = None
    # incremental-decode cache state (serve/decode.py); None outside
    # task=serve generation
    decode: Optional[DecodeState] = None
    _rng_count: int = 0

    def next_rng(self) -> jax.Array:
        if self.rng is None:
            raise RuntimeError("layer requested randomness but no rng in context")
        self._rng_count += 1
        return jax.random.fold_in(self.rng, self._rng_count)


@dataclasses.dataclass
class LayerParam:
    """Common layer hyperparameters (reference ``src/layer/param.h:15-139``)."""

    num_hidden: int = 0
    init_sigma: float = 0.01
    init_uniform: float = -1.0
    init_bias: float = 0.0
    num_channel: int = 0
    random_type: int = 0  # 0 gaussian, 1 uniform/xavier, 2 kaiming
    num_group: int = 1
    kernel_height: int = 0
    kernel_width: int = 0
    stride: int = 1
    pad_y: int = 0
    pad_x: int = 0
    no_bias: int = 0
    silent: int = 0

    def set_param(self, name: str, val: str) -> bool:
        """Consume one config key; returns True when the key was one of
        the common layer hyperparameters (the lint registry declares the
        same set as :data:`LAYER_PARAM_KEYS`)."""
        if name == "init_sigma":
            self.init_sigma = float(val)
        elif name == "init_uniform":
            self.init_uniform = float(val)
        elif name == "init_bias":
            self.init_bias = float(val)
        elif name == "random_type":
            m = {"gaussian": 0, "uniform": 1, "xavier": 1, "kaiming": 2}
            if val not in m:
                raise ValueError(f"invalid random_type {val!r}")
            self.random_type = m[val]
        elif name == "nhidden":
            self.num_hidden = int(val)
        elif name == "nchannel":
            self.num_channel = int(val)
        elif name == "ngroup":
            self.num_group = int(val)
        elif name == "kernel_size":
            self.kernel_height = self.kernel_width = int(val)
        elif name == "kernel_height":
            self.kernel_height = int(val)
        elif name == "kernel_width":
            self.kernel_width = int(val)
        elif name == "stride":
            self.stride = int(val)
        elif name == "pad":
            self.pad_y = self.pad_x = int(val)
        elif name == "pad_y":
            self.pad_y = int(val)
        elif name == "pad_x":
            self.pad_x = int(val)
        elif name == "no_bias":
            self.no_bias = int(val)
        elif name == "silent":
            self.silent = int(val)
        else:
            return False
        return True

    def rand_init_weight(self, key: jax.Array, shape: Sequence[int],
                         in_num: int, out_num: int,
                         dtype=jnp.float32) -> jnp.ndarray:
        """Weight init following ``param.h RandInitWeight`` (:113-138).

        Parity holds for random_type 0 (gaussian) and 1 (xavier/uniform)
        only.  random_type 2 (kaiming) DELIBERATELY diverges from the
        reference: ``param.h`` scales by the fan-OUT-ish
        ``num_hidden/num_channel``, which under-scales exactly the deep
        relu stacks kaiming exists for (see the round-5 GoogLeNet
        vanishing-signal diagnosis below); we use the correct
        ``sqrt(2 / fan_in)`` (He et al., 2015) instead.
        """
        shape = tuple(shape)
        if self.random_type == 0:
            return self.init_sigma * jax.random.normal(key, shape, dtype)
        if self.random_type == 1:
            a = float(np.sqrt(3.0 / (in_num + out_num)))
            if self.init_uniform > 0:
                a = self.init_uniform
            return jax.random.uniform(key, shape, dtype, minval=-a, maxval=a)
        if self.random_type == 2:
            # kaiming: sqrt(2 / fan_IN) — the in_num callers pass is the
            # per-group fan-in (conv: cin/g*kh*kw, fullc: input dim).
            # The old formula read num_hidden/num_channel, i.e. fan_OUT,
            # which under-scales exactly the deep relu stacks kaiming
            # exists for: GoogLeNet activations decayed ~3x per stage
            # (0.5 -> 2e-3 by inception 4a) and the logits sank below
            # bf16 noise, making the loss data-independent at chance.
            sigma = float(np.sqrt(2.0 / in_num)) if in_num > 0 else 0.01
            return sigma * jax.random.normal(key, shape, dtype)
        raise ValueError(f"unsupported random_type {self.random_type}")


Params = Dict[str, jnp.ndarray]


class Layer:
    """Base class for all layers.

    Subclasses override :meth:`infer_shapes`, :meth:`init_params`,
    :meth:`forward`, and optionally :meth:`set_param` / :meth:`loss`.
    A layer instance holds only static configuration; all tensors live in
    the params/buffers pytrees owned by the trainer.
    """

    # canonical config-file type name(s); first entry is the primary name
    type_names: Tuple[str, ...] = ()
    # True for loss layers (self-loop + contributes a loss term)
    is_loss: bool = False
    # keys this subclass's set_param consumes beyond LAYER_PARAM_KEYS —
    # the declared-key registry (analysis/registry.py) harvests these;
    # keep them in sync with the set_param branches
    extra_config_keys: Tuple[KeySpec, ...] = ()

    def __init__(self) -> None:
        self.param = LayerParam()
        self.name: str = ""

    # -- configuration ----------------------------------------------------
    def set_param(self, name: str, val: str) -> None:
        """Consume a config key; unknown keys are ignored (reference rule)
        unless ``strict_config = 1`` routes them through the lint
        reporter as warnings (keys declared by this layer type or known
        anywhere in the global registry stay silent — globals are
        broadcast to every layer)."""
        consumed = self.param.set_param(name, val)
        if not consumed and _STRICT_CONFIG:
            from ..analysis.conflint import report_ignored_layer_key
            report_ignored_layer_key(self, name, val)

    @classmethod
    def config_keys(cls) -> Tuple[KeySpec, ...]:
        """Every key this layer type accepts: the common LayerParam set
        plus each class's declared extras along the MRO."""
        out = list(LAYER_PARAM_KEYS)
        for klass in cls.__mro__:
            out.extend(klass.__dict__.get("extra_config_keys", ()))
        return tuple(out)

    # -- shapes -----------------------------------------------------------
    def infer_shapes(self, in_shapes: List[Shape4]) -> List[Shape4]:
        raise NotImplementedError

    # -- parameters -------------------------------------------------------
    def init_params(self, key: jax.Array, in_shapes: List[Shape4],
                    dtype=jnp.float32) -> Params:
        return {}

    def init_buffers(self, in_shapes: List[Shape4]) -> Params:
        """Non-learned state (e.g. batchnorm moving stats, fixconn table)."""
        return {}

    # -- compute ----------------------------------------------------------
    def forward(self, params: Params, buffers: Params,
                inputs: List[jnp.ndarray], ctx: ForwardContext
                ) -> Tuple[List[jnp.ndarray], Params]:
        """Return (outputs, new_buffers). Must be jax-traceable."""
        raise NotImplementedError

    # -- helpers ----------------------------------------------------------
    def check_n_inputs(self, inputs: Sequence, lo: int, hi: Optional[int] = None):
        hi = lo if hi is None else hi
        if not (lo <= len(inputs) <= hi):
            raise ShapeError(
                f"{self.type_names[0]} layer expects {lo}..{hi} inputs, got {len(inputs)}")
