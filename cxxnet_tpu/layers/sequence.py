"""Sequence-model layers: embedding, layernorm, per-position linear, gelu,
multi-head attention (dense or ring/sequence-parallel), and the LM softmax
loss.

The reference framework predates attention entirely (SURVEY.md §5.7: data is
fixed (N,C,H,W) images), so these layers have no file:line counterparts —
they exist because long-context is first-class in this framework.  They fit
the same config-driven ILayer system: a sequence node is a 4-D
(batch, 1, seq, dim) tensor, token-id inputs are (batch, 1, 1, seq), so every
existing mechanism (netconfig graph syntax, visitors/tags, checkpointing,
pairtest) applies unchanged.

Sequence parallelism: when the trainer's mesh has a ``seq`` axis, attention
runs as ring attention over ICI (``parallel/ring.py``) and the per-position
layers constrain their activations to stay seq-sharded; XLA then never
gathers the full sequence on one device.
"""

from __future__ import annotations

import warnings
from typing import List

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..analysis.schema import K
from ..parallel import ring
from .base import ForwardContext, Layer, Shape4
from .loss import LossLayerBase


def _seq_mesh(ctx: ForwardContext):
    """The mesh if sequence parallelism is active, else None."""
    mesh = getattr(ctx, "mesh", None)
    if mesh is not None and "seq" in mesh.axis_names and mesh.shape["seq"] > 1:
        return mesh
    return None


def _label_field(ctx: ForwardContext, name: str):
    """A (b, s) label field by name, or None when the key is unset or the
    forward carries no labels (eval/pred forwards pass label_vec=None —
    packing-aware layers then fall back to their unpacked behavior)."""
    if not name or ctx.labels is None or name not in ctx.labels.fields:
        return None
    return ctx.labels.fields[name]


def _single_device_attention(q, k, v, causal: bool, seg=None):
    """Single-device attention dispatch: the Pallas flash kernel on TPU
    (VMEM-resident scores; measured 3.2x the XLA chunked path forward at
    s=8192 on v5e, and the only path whose backward fits at that length),
    XLA dense/chunked otherwise.  Config key ``flash_attn = 0`` (or env
    CXXNET_NO_FLASH_ATTN=1) opts out.  ``seg`` (b, s) segment ids select
    the segment-masked variants (packed documents): the triangular-flash
    segment kernel where the grid allows, the lax fallback elsewhere —
    the two are pairtested in interpret mode (tests/test_text.py)."""
    from ..engine import opts
    from ..ops import pallas_kernels as pk
    s, hd = q.shape[2], q.shape[3]
    if (pk._on_tpu() and pk.flash_attention_available(s, hd)
            and opts.flash_attn == "1"):
        if seg is not None:
            if causal:
                return pk.flash_attention_segmented(q, k, v, seg)
        else:
            return pk.flash_attention(q, k, v, causal)
    return ring.dense_attention(q, k, v, causal=causal, seg=seg)


def seq_constraint(x: jnp.ndarray, ctx: ForwardContext) -> jnp.ndarray:
    """Pin a (b, 1, s, d) activation to the seq-sharded layout."""
    mesh = _seq_mesh(ctx)
    if mesh is None or x.shape[2] % mesh.shape["seq"] != 0:
        return x
    dp = "data" if "data" in mesh.axis_names else None
    return jax.lax.with_sharding_constraint(
        x, jax.sharding.NamedSharding(mesh, P(dp, None, "seq", None)))


class EmbeddingLayer(Layer):
    """Token embedding: (b,1,1,s) float ids -> (b,1,s,d).

    Params: "wmat" (vocab, d); with ``pos_embed = 1`` also "wpos" (s, d)
    learned positional embeddings (sequence length is static under jit, so
    the table is sized at shape inference).
    """

    type_names = ("embedding",)
    extra_config_keys = (
        K("vocab_size", "int", lo=1),
        K("pos_embed", "int", lo=0, hi=1),
        K("pos_key", "str",
          help="label field carrying per-position ids (packed documents "
               "reset positions at each doc start — io/text.py); empty = "
               "sequential 0..s-1"),
    )

    def __init__(self):
        super().__init__()
        self.vocab_size = 0
        self.pos_embed = 0
        self.pos_key = ""

    def set_param(self, name, val):
        if name == "vocab_size":
            self.vocab_size = int(val)
        elif name == "pos_embed":
            self.pos_embed = int(val)
        elif name == "pos_key":
            self.pos_key = val
        else:
            super().set_param(name, val)

    def infer_shapes(self, in_shapes: List[Shape4]) -> List[Shape4]:
        assert len(in_shapes) == 1, "embedding: 1-1 connection only"
        n, c, h, s = in_shapes[0]
        assert c == 1 and h == 1, "embedding: input must be (b,1,1,seq) ids"
        assert self.vocab_size > 0, "embedding: must set vocab_size"
        assert self.param.num_hidden > 0, "embedding: must set nhidden"
        return [(n, 1, s, self.param.num_hidden)]

    def init_params(self, key, in_shapes, dtype=jnp.float32):
        d = self.param.num_hidden
        s = in_shapes[0][3]
        kw, kp = jax.random.split(key)
        sigma = self.param.init_sigma
        params = {"wmat": sigma * jax.random.normal(
            kw, (self.vocab_size, d), dtype)}
        if self.pos_embed:
            params["wpos"] = sigma * jax.random.normal(kp, (s, d), dtype)
        return params

    def forward(self, params, buffers, inputs, ctx):
        self.check_n_inputs(inputs, 1)
        ids = inputs[0].reshape(inputs[0].shape[0], -1).astype(jnp.int32)
        out = jnp.take(params["wmat"], ids, axis=0)  # (b, s, d)
        if "wpos" in params:
            dec = getattr(ctx, "decode", None)
            pos = _label_field(ctx, self.pos_key)
            if dec is not None and dec.mode in ("step", "block"):
                # incremental decode (serve/decode.py): every row sits
                # at its own absolute position (step: one position;
                # block: W consecutive positions starting there) —
                # gather the positional rows per batch element.
                # Identical arithmetic to the sequential broadcast's row
                # at that position, so the incremental forward stays
                # bitwise equal to the full one
                pidx = jnp.clip(dec.positions.astype(jnp.int32)[:, None]
                                + jnp.arange(ids.shape[1], dtype=jnp.int32)
                                [None, :], 0,
                                params["wpos"].shape[0] - 1)
                out = out + jnp.take(params["wpos"], pidx,
                                     axis=0).astype(out.dtype)
            elif pos is not None:
                # packed documents: positions reset at each doc start —
                # gather per (b, s) position ids instead of broadcasting
                # the sequential table (eval forwards carry no label
                # fields and fall back to sequential positions)
                pidx = jnp.clip(pos.astype(jnp.int32), 0,
                                params["wpos"].shape[0] - 1)
                out = out + jnp.take(params["wpos"], pidx,
                                     axis=0).astype(out.dtype)
            else:
                out = out + params["wpos"][None, :, :].astype(out.dtype)
        out = out[:, None, :, :]
        return [seq_constraint(out, ctx)], buffers


class LayerNormLayer(Layer):
    """Layer normalization over the feature (last) axis of (b,1,s,d).

    Learned slope/bias exposed under the standard "wmat"/"bias" tags (the
    batchnorm layer does the same) so ``wmat:lr`` scoping and the weight
    visitors work.
    """

    type_names = ("layernorm",)
    extra_config_keys = (K("eps", "float", lo=0.0),)

    def __init__(self):
        super().__init__()
        self.eps = 1e-5

    def set_param(self, name, val):
        if name == "eps":
            self.eps = float(val)
        else:
            super().set_param(name, val)

    def infer_shapes(self, in_shapes: List[Shape4]) -> List[Shape4]:
        assert len(in_shapes) == 1, "layernorm: 1-1 connection only"
        return [in_shapes[0]]

    def init_params(self, key, in_shapes, dtype=jnp.float32):
        d = in_shapes[0][3]
        return {"wmat": jnp.ones((d,), dtype),
                "bias": jnp.zeros((d,), dtype)}

    def forward(self, params, buffers, inputs, ctx):
        self.check_n_inputs(inputs, 1)
        x = inputs[0]
        n, c, s, d = x.shape
        rows = n * c * s
        from ..engine import opts
        from ..ops import pallas_kernels as pk
        if (pk._on_tpu() and opts.pallas_ln in ("1", "x")  # default-on (r6)
                and pk.layernorm_pallas_supported(rows, d)):
            # single-sweep Pallas kernel: the XLA lowering left
            # ~1.9 ms/site convert_reduce fusions in the d2048 step
            # (47.9 ms over 25 sites vs 0.094 ms standalone — the fusion
            # chains behind an operand copy).  Default-on since the
            # backward went output-derived: residuals are (y, gamma,
            # beta, rstd) with y aliasing the output, so the kernel no
            # longer pins a per-site (rows, d) input copy (the round-5
            # HBM trade that OOM'd the d2048 flagship).  pallas_ln = x
            # keeps the kernel but saves the input (precision escape
            # hatch for |beta| >> |gamma| bf16 configs); pallas_ln = 0
            # restores the XLA lowering.  See doc/pallas_ln.md.
            y = pk.layernorm_pallas(x.reshape(rows, d), params["wmat"],
                                    params["bias"], self.eps, None,
                                    opts.pallas_ln == "x")
            return [y.reshape(x.shape)], buffers
        x32 = x.astype(jnp.float32)
        mean = x32.mean(axis=-1, keepdims=True)
        if x.dtype == jnp.bfloat16:
            # single-pass moments (E[x^2]-E[x]^2): one reduce fusion over
            # x instead of two chained ones — measured -19 ms/step on the
            # d2048 flagship.  The formula cancels for rows with
            # mean/std beyond ~2^11, but bf16 INPUTS quantize away at
            # mean/std ~2^8 already, so nothing is lost for bf16 models;
            # f32 inputs keep the cancellation-robust two-pass form.
            m2 = jnp.square(x32).mean(axis=-1, keepdims=True)
            var = jnp.maximum(m2 - jnp.square(mean), 0.0)
        else:
            var = jnp.square(x32 - mean).mean(axis=-1, keepdims=True)
        y = (x32 - mean) * jax.lax.rsqrt(var + self.eps)
        y = y * params["wmat"].astype(jnp.float32) \
            + params["bias"].astype(jnp.float32)
        return [y.astype(x.dtype)], buffers


class SeqFullcLayer(Layer):
    """Per-position linear on the last axis: (b,1,s,d) -> (b,1,s,nhidden).

    Unlike ``fullc`` (which flattens the node to (b, c*h*w) — correct for
    image heads, wrong for sequences), this is position-wise.  Weight "wmat"
    (nhidden, d), bias "bias" (nhidden,) — same tags/layout as fullc.
    """

    type_names = ("seq_fullc",)

    def infer_shapes(self, in_shapes: List[Shape4]) -> List[Shape4]:
        assert len(in_shapes) == 1, "seq_fullc: 1-1 connection only"
        n, c, s, d = in_shapes[0]
        assert c == 1, "seq_fullc: input must be (b,1,s,d)"
        assert self.param.num_hidden > 0, "seq_fullc: must set nhidden"
        return [(n, 1, s, self.param.num_hidden)]

    def init_params(self, key, in_shapes, dtype=jnp.float32):
        d = in_shapes[0][3]
        nh = self.param.num_hidden
        kw, kb = jax.random.split(key)
        params = {"wmat": self.param.rand_init_weight(kw, (nh, d), d, nh, dtype)}
        if not self.param.no_bias:
            params["bias"] = jnp.full((nh,), self.param.init_bias, dtype)
        return params

    def forward(self, params, buffers, inputs, ctx):
        self.check_n_inputs(inputs, 1)
        x = inputs[0]
        w = params["wmat"].astype(x.dtype)
        out = jnp.einsum("bcsd,nd->bcsn", x, w)
        if "bias" in params:
            out = out + params["bias"].astype(x.dtype)
        return [seq_constraint(out, ctx)], buffers


class AttentionLayer(Layer):
    """Multi-head self-attention on (b,1,s,d).

    Params: "wqkv" (3d, d), "wout" (d, d), biases "bqkv"/"bout" unless
    ``no_bias``.  Config: ``nhead`` (required), ``causal = 0|1``.

    When the trainer mesh has a ``seq`` axis the score computation runs as
    ring attention (K/V rotating over ICI, online softmax — see
    ``parallel/ring.py``); otherwise dense attention.  Head count must
    divide d; when a ``model`` axis exists and divides nhead, heads are
    additionally sharded over it inside the ring (Ulysses-style hybrid).
    """

    type_names = ("attention",)
    extra_config_keys = (
        K("nhead", "int", lo=1), K("causal", "int", lo=0, hi=1),
        K("segment_key", "str",
          help="label field with per-position segment ids (packed "
               "documents, io/text.py): attention is block-diagonal — "
               "cross-segment scores masked, segment 0 = padding"),
    )

    def __init__(self):
        super().__init__()
        self.nhead = 0
        self.causal = 0
        self.segment_key = ""

    def set_param(self, name, val):
        if name == "nhead":
            self.nhead = int(val)
        elif name == "causal":
            self.causal = int(val)
        elif name == "segment_key":
            self.segment_key = val
        else:
            super().set_param(name, val)

    def infer_shapes(self, in_shapes: List[Shape4]) -> List[Shape4]:
        assert len(in_shapes) == 1, "attention: 1-1 connection only"
        n, c, s, d = in_shapes[0]
        assert c == 1, "attention: input must be (b,1,s,d)"
        assert self.nhead > 0, "attention: must set nhead"
        assert d % self.nhead == 0, "attention: nhead must divide dim"
        return [in_shapes[0]]

    def init_params(self, key, in_shapes, dtype=jnp.float32):
        d = in_shapes[0][3]
        kq, ko = jax.random.split(key)
        params = {
            "wqkv": self.param.rand_init_weight(kq, (3 * d, d), d, 3 * d, dtype),
            "wout": self.param.rand_init_weight(ko, (d, d), d, d, dtype),
        }
        if not self.param.no_bias:
            params["bqkv"] = jnp.zeros((3 * d,), dtype)
            params["bout"] = jnp.zeros((d,), dtype)
        return params

    def forward(self, params, buffers, inputs, ctx):
        self.check_n_inputs(inputs, 1)
        x = inputs[0]
        b, _, s, d = x.shape
        h = self.nhead
        hd = d // h
        qkv = jnp.einsum("bcsd,nd->bcsn", x, params["wqkv"].astype(x.dtype))
        if "bqkv" in params:
            qkv = qkv + params["bqkv"].astype(x.dtype)
        qkv = qkv.reshape(b, s, 3, h, hd).transpose(2, 0, 3, 1, 4)
        q, k, v = qkv[0], qkv[1], qkv[2]  # (b, h, s, hd)
        dec = getattr(ctx, "decode", None)
        if dec is not None:
            att = self._decode_attention(dec, q, k, v)
            att = att.transpose(0, 2, 1, 3).reshape(b, 1, s, d)
            out = jnp.einsum("bcsd,nd->bcsn", att,
                             params["wout"].astype(x.dtype))
            if "bout" in params:
                out = out + params["bout"].astype(x.dtype)
            return [out], buffers
        seg = _label_field(ctx, self.segment_key)
        if seg is not None:
            seg = seg.astype(jnp.int32)  # (b, s) doc segments; 0 = pad
        mesh = _seq_mesh(ctx)
        if mesh is not None and s % mesh.shape["seq"] == 0:
            att = ring.sharded_attention(q, k, v, mesh,
                                         causal=bool(self.causal), seg=seg)
        else:
            if mesh is not None:
                warnings.warn(
                    f"attention: seq length {s} is not divisible by the "
                    f"seq mesh axis ({mesh.shape['seq']}); falling back to "
                    "dense attention, which gathers the full sequence on "
                    "one device", stacklevel=2)
            att = _single_device_attention(q, k, v, bool(self.causal),
                                           seg=seg)
        att = att.transpose(0, 2, 1, 3).reshape(b, 1, s, d)
        out = jnp.einsum("bcsd,nd->bcsn", att, params["wout"].astype(x.dtype))
        if "bout" in params:
            out = out + params["bout"].astype(x.dtype)
        return [seq_constraint(out, ctx)], buffers

    def _decode_attention(self, dec, q, k, v):
        """Cache-aware attention for incremental decode (serve/decode.py).

        Prefill captures this layer's fresh (k, v) into the decode cache
        and otherwise runs the stock causal path, so prefill logits are
        byte-identical to a plain eval forward.  Step mode (seq len 1)
        scatters the new position's (k, v) into the cache and attends
        over the whole ``max_seqlen`` cache under the length mask
        ``arange(S) <= position``: masked scores get ``ring.NEG_INF``
        exactly like the causal mask in :func:`ring._block_scores`,
        softmax to exactly 0.0, and contribute nothing to the p·V
        reduction — which is how the incremental logits stay bitwise
        equal to the full forward at f32 even though never-written cache
        slots hold stale (finite) garbage.  Block mode is step mode over
        ``W`` consecutive positions (speculative verify / chunked
        prefill): scatter all ``W`` columns, and query ``w``'s mask is
        ``arange(S) <= position + w`` — so row ``w``'s reduction is the
        sequential step's at that position, bitwise.
        """
        key = getattr(self, "_decode_key", None)
        assert key is not None, \
            "attention: decode forward without an engine-stamped cache key"
        assert self.causal, "incremental decode requires causal = 1"
        if dec.mode not in ("step", "block"):
            dec.caches[key] = {"k": k, "v": v}
            return _single_device_attention(q, k, v, True, seg=None)
        b, h, s, hd = q.shape
        if dec.mode == "step":
            assert s == 1, f"decode step expects seq len 1, got {s}"
        cache = dec.caches[key]
        rows = jnp.arange(b)
        if dec.mode == "step":
            # advanced indices at dims 0 and 2 with a slice between: the
            # broadcast (b,) x (b,) pair leads the result, giving
            # (b, h, hd) update slots — exactly k[:, :, 0, :]'s shape
            ck = cache["k"].at[rows, :, dec.positions].set(
                k[:, :, 0, :].astype(cache["k"].dtype))
            cv = cache["v"].at[rows, :, dec.positions].set(
                v[:, :, 0, :].astype(cache["v"].dtype))
            # query w = 0 sees columns <= positions
            qoff = jnp.zeros((1,), jnp.int32)
        else:
            # block mode: W consecutive columns per row.  The (b, 1) x
            # (b, W) advanced-index pair broadcasts to (b, W) and leads
            # the result, so updates are (b, W, h, hd) — k transposed.
            # ``mode="drop"`` discards columns past the cache end (a
            # slot near its length limit verifies a block whose tail
            # the scheduler never emits from)
            idx = dec.positions[:, None] + jnp.arange(s)[None, :]
            ck = cache["k"].at[rows[:, None], :, idx].set(
                k.transpose(0, 2, 1, 3).astype(cache["k"].dtype),
                mode="drop")
            cv = cache["v"].at[rows[:, None], :, idx].set(
                v.transpose(0, 2, 1, 3).astype(cache["v"].dtype),
                mode="drop")
            # query w sees columns <= positions + w: causal within the
            # block, length-masked against the cache — each row's
            # reduction is bitwise the sequential step's at that
            # position
            qoff = jnp.arange(s, dtype=jnp.int32)
        dec.caches[key] = {"k": ck, "v": cv}
        scale = 1.0 / (hd ** 0.5)
        scores = jnp.einsum("bhqd,bhkd->bhqk", q,
                            ck.astype(q.dtype),
                            preferred_element_type=jnp.float32) * scale
        mask = jnp.arange(ck.shape[2])[None, None, :] \
            <= (dec.positions[:, None] + qoff[None, :])[:, :, None]
        scores = jnp.where(mask[:, None, :, :], scores, ring.NEG_INF)
        p = jax.nn.softmax(scores, axis=-1)
        return jnp.einsum("bhqk,bhkd->bhqd", p,
                          cv.astype(p.dtype)).astype(q.dtype)


class SoftmaxSeqLayer(LossLayerBase):
    """Per-position softmax + cross-entropy LM loss (self-loop).

    Input (b,1,s,V); label field is (b, s) token ids (declare
    ``label_vec[0,s) = label`` so the label vector carries one id per
    position).  Loss is the mean per-token cross-entropy, summed over the
    batch with the same ``grad_scale/(batch·update_period)`` scaling as the
    image losses (inherited from LossLayerBase).  forward is overridden
    because the (b, s, V) structure must survive — the base class flattens
    to (b, s*V).

    ``packed = 1`` (document-packed rows, io/text.py): target ids < 0
    mark positions whose next token crosses a document boundary or is
    padding — they contribute zero loss AND zero gradient, and the
    per-instance mean divides by the VALID-token count, so a row's loss
    weight does not depend on how many doc boundaries it packed.
    """

    type_names = ("softmax_seq",)
    extra_config_keys = (
        K("packed", "int", lo=0, hi=1,
          help="mask target ids < 0 (packed-document boundaries/padding) "
               "out of the loss; mean over valid tokens only"),
    )

    def __init__(self):
        super().__init__()
        self.packed = 0

    def set_param(self, name, val):
        if name == "packed":
            self.packed = int(val)
        else:
            super().set_param(name, val)

    def forward(self, params, buffers, inputs, ctx):
        self.check_n_inputs(inputs, 1)
        x = inputs[0]  # (b, 1, s, V)
        out = jax.nn.softmax(x, axis=-1)
        if ctx.labels is not None and ctx.train:
            y = ctx.labels.get(self.target)  # (b, s) float ids
            logp = jax.nn.log_softmax(x[:, 0].astype(jnp.float32), axis=-1)
            yi = y.astype(jnp.int32)
            if self.packed:
                valid = (y >= 0).astype(jnp.float32)
                tok = jnp.take_along_axis(
                    logp, jnp.maximum(yi, 0)[:, :, None], axis=2)[:, :, 0]
                per_inst = -(tok * valid).sum(axis=1) \
                    / jnp.maximum(valid.sum(axis=1), 1.0)
            else:
                tok = jnp.take_along_axis(
                    logp, yi[:, :, None], axis=2)[:, :, 0]
                per_inst = -tok.mean(axis=1)  # mean per-token nats
            if ctx.labels.mask is not None:
                # tail-batch replica padding is masked out, same contract
                # as LossLayerBase (DataBatch.tail_mask_padd)
                per_inst = per_inst * ctx.labels.mask.astype(per_inst.dtype)
            ctx.losses.append(per_inst.sum() * (self.grad_scale * ctx.loss_scale))
        return [out], buffers
