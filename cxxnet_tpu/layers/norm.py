"""batch_norm and dropout layers.

Reference: ``src/layer/batch_norm_layer-inl.hpp`` and
``dropout_layer-inl.hpp``.
"""

from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp

from ..analysis.schema import K
from ..ops import nn as N
from .base import ForwardContext, Layer, Params, Shape4


class BatchNormLayer(Layer):
    """Per-channel (conv) or per-feature (fc) batch normalization.

    Parity notes (batch_norm_layer-inl.hpp):
    * branch on fc vs conv by ``size(1)==1`` (:36-42);
    * learnable slope is exposed under tag "wmat" and bias under "bias"
      (:26-29), so tag-scoped hyperparameters apply;
    * the reference uses *batch statistics at eval time too* (doc/layer.md:258
      records this caveat) — we reproduce that by default, and additionally
      keep exponential moving averages in buffers; set ``moving_average = 1``
      to use them at eval (the modern behavior the reference lacks).
    """

    type_names = ("batch_norm",)
    extra_config_keys = (
        K("init_slope", "float"), K("eps", "float", lo=0.0),
        K("moving_average", "int", lo=0, hi=1),
        K("bn_momentum", "float", lo=0.0, hi=1.0),
    )

    def __init__(self):
        super().__init__()
        self.init_slope = 1.0
        self.init_bias = 0.0
        self.eps = 1e-10
        self.moving_average = 0
        self.bn_momentum = 0.9

    def set_param(self, name, val):
        if name == "init_slope":
            self.init_slope = float(val)
        elif name == "init_bias":
            self.init_bias = float(val)
        elif name == "eps":
            self.eps = float(val)
        elif name == "moving_average":
            self.moving_average = int(val)
        elif name == "bn_momentum":
            self.bn_momentum = float(val)
        else:
            super().set_param(name, val)

    @staticmethod
    def _channel_axis(shape: Shape4) -> int:
        return 3 if shape[1] == 1 else 1

    def infer_shapes(self, in_shapes: List[Shape4]) -> List[Shape4]:
        assert len(in_shapes) == 1, "batch_norm: 1-1 connection only"
        return [in_shapes[0]]

    def init_params(self, key, in_shapes, dtype=jnp.float32):
        c = in_shapes[0][self._channel_axis(in_shapes[0])]
        return {"wmat": jnp.full((c,), self.init_slope, dtype),
                "bias": jnp.full((c,), self.init_bias, dtype)}

    def init_buffers(self, in_shapes):
        c = in_shapes[0][self._channel_axis(in_shapes[0])]
        return {"moving_mean": jnp.zeros((c,), jnp.float32),
                "moving_var": jnp.ones((c,), jnp.float32)}

    def forward(self, params, buffers, inputs, ctx):
        self.check_n_inputs(inputs, 1)
        x = inputs[0]
        ax = self._channel_axis(x.shape)
        reduce_axes = tuple(i for i in range(4) if i != ax)
        bshape = [1, 1, 1, 1]
        bshape[ax] = x.shape[ax]
        xf = x.astype(jnp.float32)
        mask = ctx.labels.mask if (ctx.train and ctx.labels is not None) \
            else None
        if ctx.train or not self.moving_average:
            if mask is not None:
                # tail-batch replica padding is excluded from the batch
                # statistics (the reference computes stats over the
                # re-plumbed real batch only, AdjustBatchSize)
                m4 = mask.astype(jnp.float32).reshape(-1, 1, 1, 1)
                denom = jnp.maximum(
                    m4.sum() * (xf.size / xf.shape[0] / xf.shape[ax]), 1.0)
                mean = (xf * m4).sum(reduce_axes) / denom
                var = (jnp.square(xf - mean.reshape(bshape)) * m4
                       ).sum(reduce_axes) / denom
            else:
                mean = xf.mean(reduce_axes)
                var = jnp.square(xf - mean.reshape(bshape)).mean(reduce_axes)
        else:
            mean = buffers["moving_mean"]
            var = buffers["moving_var"]
        slope = params["wmat"].astype(jnp.float32)
        bias = params["bias"].astype(jnp.float32)
        inv = jax.lax.rsqrt(var + self.eps)
        out = (xf - mean.reshape(bshape)) * inv.reshape(bshape)
        out = out * slope.reshape(bshape) + bias.reshape(bshape)
        new_buffers = buffers
        if ctx.train:
            m = self.bn_momentum
            new_buffers = {
                "moving_mean": m * buffers["moving_mean"]
                + (1 - m) * jax.lax.stop_gradient(mean),
                "moving_var": m * buffers["moving_var"]
                + (1 - m) * jax.lax.stop_gradient(var),
            }
        return [out.astype(x.dtype)], new_buffers


class DropoutLayer(Layer):
    """Self-loop dropout (dropout_layer-inl.hpp:11-66): mask =
    threshold(uniform, pkeep) / pkeep at train, identity at eval."""

    type_names = ("dropout",)
    extra_config_keys = (
        K("threshold", "float", lo=0.0, hi=0.999,
          help="drop probability (1 - pkeep)"),
    )

    def __init__(self):
        super().__init__()
        self.threshold = 0.0

    def set_param(self, name, val):
        if name == "threshold":
            self.threshold = float(val)
        else:
            super().set_param(name, val)

    def infer_shapes(self, in_shapes: List[Shape4]) -> List[Shape4]:
        assert len(in_shapes) == 1, "dropout: 1-1 connection only"
        assert 0.0 <= self.threshold < 1.0, "dropout: invalid threshold"
        return [in_shapes[0]]

    def forward(self, params, buffers, inputs, ctx):
        self.check_n_inputs(inputs, 1)
        x = inputs[0]
        if not ctx.train or self.threshold == 0.0:
            return [x], buffers
        pkeep = 1.0 - self.threshold
        mask = N.dropout_mask(ctx.next_rng(), x.shape, pkeep, x.dtype)
        return [x * mask], buffers
