"""Convolution, pooling, LRN, and insanity-pooling layers.

Reference: ``src/layer/convolution_layer-inl.hpp`` (im2col GEMM with grouped
conv), ``cudnn_convolution_layer-inl.hpp`` (fast path), ``pooling_layer`` /
``cudnn_pooling_layer``, ``lrn_layer``, ``insanity_pooling_layer``.  On TPU
all of these lower through XLA: conv → ConvGeneralDilated on the MXU (the
cuDNN analogue), pooling → ReduceWindow, LRN → channel-windowed reduction.
The reference's temp_col chunking (``temp_col_max``) exists to bound im2col
scratch memory; XLA handles conv tiling itself, so the knob is accepted and
ignored.
"""

from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp

from ..analysis.schema import K
from ..ops import nn as N
from .base import ForwardContext, Layer, Params, Shape4


class ConvolutionLayer(Layer):
    """Grouped 2-D convolution (conv config name).

    Weight tagged "wmat" with shape (out_c, in_c/ngroup, kh, kw) — the 4-D
    equivalent of the reference's (group, out_c/group, in_c/group*kh*kw)
    layout (convolution_layer-inl.hpp:29-31); bias "bias" (out_c,).
    """

    type_names = ("conv",)
    extra_config_keys = (
        K("space_to_depth", "int", lo=0, hi=1,
          help="lower a strided conv through space-to-depth"),
        K("temp_col_max", "int",
          help="accepted and ignored: XLA tiles conv scratch itself"),
    )

    def __init__(self):
        super().__init__()
        self.space_to_depth = 0
        # set by the trainer under ``input_s2d = 1``: the batch arrives
        # pre-transformed to space-to-depth layout (staged once, outside
        # the step), so forward runs the dense stride-1 conv
        self.s2d_input = 0
        # set by the trainer's relu/bias->pool reorder: the bias add (and
        # its gradient reduce) moves to the downstream max pool's
        # stride^2-smaller tensor (max(z + b) == max(z) + b per channel)
        self.defer_bias = 0

    def set_param(self, name: str, val: str) -> None:
        if name == "space_to_depth":
            self.space_to_depth = int(val)
        super().set_param(name, val)

    def infer_shapes(self, in_shapes: List[Shape4]) -> List[Shape4]:
        assert len(in_shapes) == 1, "conv: 1-1 connection only"
        p = self.param
        assert p.kernel_height > 0 and p.kernel_width > 0, \
            "conv: must set kernel_size correctly"
        assert p.num_channel > 0, "conv: must set nchannel correctly"
        n, c, h, w = in_shapes[0]
        assert c % p.num_group == 0 and p.num_channel % p.num_group == 0, \
            "conv: channels must divide ngroup"
        oh = N.conv_out_size(h, p.kernel_height, p.stride, p.pad_y)
        ow = N.conv_out_size(w, p.kernel_width, p.stride, p.pad_x)
        assert oh > 0 and ow > 0, "conv: kernel/stride exceed input size"
        return [(n, p.num_channel, oh, ow)]

    def init_params(self, key, in_shapes, dtype=jnp.float32):
        p = self.param
        n, c, h, w = in_shapes[0]
        in_per_group = c // p.num_group
        fan_in = in_per_group * p.kernel_height * p.kernel_width
        fan_out = (p.num_channel // p.num_group) * p.kernel_height * p.kernel_width
        kw_, kb = jax.random.split(key)
        wmat = p.rand_init_weight(
            kw_, (p.num_channel, in_per_group, p.kernel_height, p.kernel_width),
            fan_in, fan_out, dtype)
        params = {"wmat": wmat}
        if not p.no_bias:
            params["bias"] = jnp.full((p.num_channel,), p.init_bias, dtype)
        return params

    def forward(self, params, buffers, inputs, ctx):
        self.check_n_inputs(inputs, 1)
        p = self.param
        x = inputs[0]
        if self.s2d_input:
            out = N.conv2d_pres2d(x, params["wmat"], stride=p.stride)
            if "bias" in params and not self.defer_bias:
                out = out + params["bias"].astype(out.dtype).reshape(
                    1, -1, 1, 1)
            return [out], buffers
        if ("bias" in params and not self.space_to_depth
                and not self.defer_bias
                and N.use_fast_wgrad(x.shape[1], p.stride, p.num_group)):
            out = N.conv_bias_fast(x, params["wmat"], params["bias"],
                                   p.stride, p.pad_y, p.pad_x)
            return [out], buffers
        if self.space_to_depth and p.stride > 1 and p.num_group == 1:
            out = N.conv2d_s2d(x, params["wmat"], stride=p.stride,
                               pad_y=p.pad_y, pad_x=p.pad_x)
        else:
            out = N.conv2d(x, params["wmat"], stride=p.stride,
                           pad_y=p.pad_y, pad_x=p.pad_x, num_group=p.num_group)
        if "bias" in params and not self.defer_bias:
            out = out + params["bias"].astype(out.dtype).reshape(1, -1, 1, 1)
        return [out], buffers


class _PoolingBase(Layer):
    """Pooling base; supports ``pad``/``pad_y``/``pad_x`` (a superset of the
    reference, whose pooling has no padding — needed for same-size inception
    pool branches in GoogLeNet)."""

    def infer_shapes(self, in_shapes: List[Shape4]) -> List[Shape4]:
        assert len(in_shapes) == 1, "pooling: 1-1 connection only"
        p = self.param
        assert p.kernel_height > 0 and p.kernel_width > 0, \
            "pooling: must set kernel_size correctly"
        n, c, h, w = in_shapes[0]
        assert p.kernel_height <= h + 2 * p.pad_y \
            and p.kernel_width <= w + 2 * p.pad_x, \
            "pooling: kernel size exceeds input"
        assert p.pad_y < p.kernel_height and p.pad_x < p.kernel_width, \
            "pooling: pad must be smaller than kernel (a window fully inside " \
            "the padding would produce -inf/0 garbage)"
        return [(n, c,
                 N.pool_out_size_padded(h, p.kernel_height, p.stride, p.pad_y),
                 N.pool_out_size_padded(w, p.kernel_width, p.stride, p.pad_x))]


class MaxPoolingLayer(_PoolingBase):
    type_names = ("max_pooling",)

    # counterpart of ReluLayer.defer_to_pool (the relu->pool reorder):
    # apply the deferred relu to the pooled output — max(relu(x)) ==
    # relu(max(x)) (relu is monotone; -inf pool padding is excluded
    # either way), and gradients agree a.e. (argmax ties that differ
    # all receive zero gradient through the relu mask)
    relu_after = False
    # key of an upstream conv whose bias add was deferred through this
    # pool (max commutes with a per-channel constant); the executor
    # injects the bias under "deferred_bias" — see net.conn_params
    deferred_bias_key = None

    def forward(self, params, buffers, inputs, ctx):
        p = self.param
        if self.relu_after and "deferred_bias" not in params:
            # deferred relu with no bias riding along: the fusable form
            # (pool_relu_fuse folds the relu mask into the Pallas unpool;
            # a deferred bias would sit between pool and relu, so that
            # combination keeps the unfused pair below)
            out = N.max_pool2d_relu(inputs[0], p.kernel_height,
                                    p.kernel_width, p.stride,
                                    p.pad_y, p.pad_x)
            return [out], buffers
        out = N.max_pool2d(inputs[0], p.kernel_height, p.kernel_width,
                           p.stride, p.pad_y, p.pad_x)
        if "deferred_bias" in params:
            out = out + params["deferred_bias"].astype(out.dtype).reshape(
                1, -1, 1, 1)
        if self.relu_after:
            from .activation import apply_relu
            out = apply_relu(out)
        return [out], buffers


class ReluMaxPoolingLayer(_PoolingBase):
    """relu fused into max pooling (layer_impl-inl.hpp:55-56).  Under
    ``pool_relu_reorder = 1`` (default) computed as relu(pool(x)) — same
    math (max commutes with relu), relu on the stride^2-smaller pooled
    tensor; ``= 0`` restores the reference pool(relu(x)) order."""

    type_names = ("relu_max_pooling",)

    def forward(self, params, buffers, inputs, ctx):
        from ..engine import opts
        from .activation import apply_relu
        p = self.param
        if opts.pool_relu_reorder != "1":
            x = apply_relu(inputs[0])
            return [N.max_pool2d(x, p.kernel_height, p.kernel_width,
                                 p.stride, p.pad_y, p.pad_x)], buffers
        return [N.max_pool2d_relu(inputs[0], p.kernel_height,
                                  p.kernel_width, p.stride,
                                  p.pad_y, p.pad_x)], buffers


class SumPoolingLayer(_PoolingBase):
    type_names = ("sum_pooling",)

    def forward(self, params, buffers, inputs, ctx):
        p = self.param
        return [N.sum_pool2d(inputs[0], p.kernel_height, p.kernel_width,
                             p.stride, p.pad_y, p.pad_x)], buffers


class AvgPoolingLayer(_PoolingBase):
    type_names = ("avg_pooling",)

    def forward(self, params, buffers, inputs, ctx):
        p = self.param
        return [N.avg_pool2d(inputs[0], p.kernel_height, p.kernel_width,
                             p.stride, p.pad_y, p.pad_x)], buffers


class InsanityPoolingLayer(_PoolingBase):
    """Stochastic-neighborhood max pooling, exact reference semantics
    (insanity_pooling_layer-inl.hpp:13-49 fwd, :150-210 bwd).

    Train time: every input position's read is randomly redirected to
    itself or one of its 4 neighbors (bands of a uniform mask, widths
    (1-keep)/4, edge-clamped), and max pooling runs over the jittered
    image; the backward propagates to every tied position of the jittered
    image at the window position (see ops.nn.insanity_max_pool).  Eval is
    plain max pooling.  ``keep`` config (reference SetParam "keep",
    default 1.0 = no jitter).
    """

    type_names = ("insanity_max_pooling",)
    extra_config_keys = (
        K("keep", "float", lo=0.0, hi=1.0, help="jitter keep probability"),
    )

    def __init__(self):
        super().__init__()
        self.p_keep = 1.0

    def set_param(self, name: str, val: str) -> None:
        if name == "keep":
            self.p_keep = float(val)
        super().set_param(name, val)

    def infer_shapes(self, in_shapes: List[Shape4]) -> List[Shape4]:
        assert self.param.pad_y == 0 and self.param.pad_x == 0, \
            "insanity_max_pooling does not support padding (neither does the "\
            "reference's, insanity_pooling_layer-inl.hpp)"
        return super().infer_shapes(in_shapes)

    def forward(self, params, buffers, inputs, ctx):
        p = self.param
        x = inputs[0]
        if not ctx.train:
            return [N.max_pool2d(x, p.kernel_height, p.kernel_width,
                                 p.stride)], buffers
        mask = jax.random.uniform(ctx.next_rng(), x.shape, jnp.float32)
        return [N.insanity_max_pool(x, mask, p.kernel_height, p.kernel_width,
                                    p.stride, self.p_keep)], buffers


class LRNLayer(Layer):
    """Cross-channel local response normalization (lrn_layer-inl.hpp:11-89)."""

    type_names = ("lrn",)
    extra_config_keys = (
        K("local_size", "int", lo=1), K("alpha", "float"),
        K("beta", "float"), K("knorm", "float"),
    )

    def __init__(self):
        super().__init__()
        self.knorm = 1.0
        self.nsize = 3
        self.alpha = 0.001
        self.beta = 0.75

    def set_param(self, name, val):
        if name == "local_size":
            self.nsize = int(val)
        elif name == "alpha":
            self.alpha = float(val)
        elif name == "beta":
            self.beta = float(val)
        elif name == "knorm":
            self.knorm = float(val)
        else:
            super().set_param(name, val)

    def infer_shapes(self, in_shapes: List[Shape4]) -> List[Shape4]:
        assert len(in_shapes) == 1, "lrn: 1-1 connection only"
        return [in_shapes[0]]

    def forward(self, params, buffers, inputs, ctx):
        self.check_n_inputs(inputs, 1)
        return [N.lrn(inputs[0], self.nsize, self.alpha, self.beta,
                      self.knorm)], buffers
