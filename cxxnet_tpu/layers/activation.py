"""Activation-family layers: relu / sigmoid / tanh / softplus / xelu /
insanity / prelu / bias.

Reference: ``src/layer/activation_layer-inl.hpp`` + ``op.h`` (elementwise op
structs), ``xelu_layer-inl.hpp``, ``insanity_layer-inl.hpp``,
``prelu_layer-inl.hpp``, ``bias_layer-inl.hpp``.  The reference pairs each
forward op with a hand-written gradient op; here the forward alone defines the
layer and jax.grad supplies the exact same gradients.

``softplus`` has an enum and a name in the reference but no factory case
(``layer_impl-inl.hpp:74`` errors on it); we implement it for real.
"""

from __future__ import annotations

from typing import List, Tuple

import jax
import jax.numpy as jnp

from ..analysis.schema import K
from .base import ForwardContext, Layer, Params, Shape4
from ..engine import opts

# relu backward formulation: "out" (default) masks the gradient from the
# relu OUTPUT via a custom VJP (reference op.h relu_grad semantics; saves
# the pre-activation residual); "xla" uses plain jnp.maximum and lets
# jax/XLA pick (residual = mask from input).  Toggle for A/B measurement.
# (config key relu_vjp / env CXXNET_RELU_VJP -> engine.opts)


class _UnaryLayer(Layer):
    """1-in 1-out elementwise layer, shape-preserving."""

    def infer_shapes(self, in_shapes: List[Shape4]) -> List[Shape4]:
        assert len(in_shapes) == 1, f"{self.type_names[0]}: 1-1 connection only"
        return [in_shapes[0]]

    def _fn(self, x: jnp.ndarray, ctx: ForwardContext) -> jnp.ndarray:
        raise NotImplementedError

    def forward(self, params, buffers, inputs, ctx):
        self.check_n_inputs(inputs, 1)
        return [self._fn(inputs[0], ctx)], buffers


@jax.custom_vjp
def _relu_out_grad(x: jnp.ndarray) -> jnp.ndarray:
    return jnp.maximum(x, 0)


def _relu_fwd(x):
    out = jnp.maximum(x, 0)
    return out, out  # residual is the OUTPUT, not the pre-activation


def _relu_bwd(out, dy):
    return (jnp.where(out > 0, dy, 0).astype(dy.dtype),)


_relu_out_grad.defvjp(_relu_fwd, _relu_bwd)


def apply_relu(x: jnp.ndarray) -> jnp.ndarray:
    """relu under the configured VJP formulation (see ReluLayer)."""
    if opts.relu_vjp == "xla":
        return jnp.maximum(x, 0)
    return _relu_out_grad(x)


class ReluLayer(_UnaryLayer):
    type_names = ("relu",)

    # set by the trainer's relu->max_pool reorder (engine option
    # pool_relu_reorder): max pooling commutes with relu, so the relu
    # moves AFTER the pool — this layer passes through and the pool
    # applies it on the (stride^2-smaller) pooled tensor, eliminating a
    # full-size relu-backward HBM pass
    defer_to_pool = False

    def _fn(self, x, ctx):
        # Gradient masked from the OUTPUT (reference op.h relu_grad uses the
        # forward output too).  jax.nn.relu's VJP masks from the
        # pre-activation, which forces XLA to keep BOTH conv-out and
        # relu-out alive to the backward pass — an extra full-activation
        # HBM write per conv+relu pair (~1.3 GB/step on AlexNet b1024).
        if self.defer_to_pool:
            return x
        return apply_relu(x)


class SigmoidLayer(_UnaryLayer):
    type_names = ("sigmoid",)

    def _fn(self, x, ctx):
        return jax.nn.sigmoid(x)


class TanhLayer(_UnaryLayer):
    type_names = ("tanh",)

    def _fn(self, x, ctx):
        return jnp.tanh(x)


class SoftplusLayer(_UnaryLayer):
    type_names = ("softplus",)

    def _fn(self, x, ctx):
        return jax.nn.softplus(x)


class GeluLayer(_UnaryLayer):
    """Gaussian error linear unit (tanh approximation) — no reference
    counterpart (the reference predates gelu); standard for the sequence
    model family."""

    type_names = ("gelu",)

    def _fn(self, x, ctx):
        return jax.nn.gelu(x)


class XeluLayer(_UnaryLayer):
    """Leaky relu with divisor b: x>0 ? x : x/b (op.h:51-61; default b=5)."""

    type_names = ("xelu",)
    extra_config_keys = (K("b", "float", help="leak divisor"),)

    def __init__(self):
        super().__init__()
        self.b = 5.0

    def set_param(self, name, val):
        if name == "b":
            self.b = float(val)
        else:
            super().set_param(name, val)

    def _fn(self, x, ctx):
        return jnp.where(x > 0, x, x / self.b)


class InsanityLayer(_UnaryLayer):
    """Randomized leaky relu (insanity_layer-inl.hpp:13-102).

    Train: per-element random divisor in [lb, ub]; eval: fixed mean divisor.
    The [lb, ub] range anneals toward its midpoint between calm_start and
    calm_end steps; the annealed bounds are computed from the epoch counter in
    closed form (the reference mutates lb_/ub_ in place per step).
    """

    type_names = ("insanity",)
    extra_config_keys = (
        K("lb", "float"), K("ub", "float"),
        K("calm_start", "int", lo=0), K("calm_end", "int", lo=0),
    )

    def __init__(self):
        super().__init__()
        self.lb = 5.0
        self.ub = 10.0
        self.calm_start = 0
        self.calm_end = 0

    def set_param(self, name, val):
        if name == "lb":
            self.lb = float(val)
        elif name == "ub":
            self.ub = float(val)
        elif name == "calm_start":
            self.calm_start = int(val)
        elif name == "calm_end":
            self.calm_end = int(val)
        else:
            super().set_param(name, val)

    def _bounds(self, step):
        if self.calm_end <= self.calm_start:
            return self.lb, self.ub
        mid = (self.lb + self.ub) / 2.0
        delta = (self.ub - mid) / (self.calm_end - self.calm_start)
        t = jnp.clip(step - self.calm_start, 0, self.calm_end - self.calm_start)
        return self.lb + delta * t, self.ub - delta * t

    def _fn(self, x, ctx):
        if ctx.train:
            lb, ub = self._bounds(ctx.epoch)
            u = jax.random.uniform(ctx.next_rng(), x.shape, x.dtype)
            divisor = u * (ub - lb) + lb
            return jnp.where(x > 0, x, x / divisor)
        mean = (self.lb + self.ub) / 2.0
        return jnp.where(x > 0, x, x / mean)


class PReluLayer(_UnaryLayer):
    """Learnable per-channel slope (prelu_layer-inl.hpp:47-173).

    out = x > 0 ? x : x * clip(slope * noise, 0, 1); the slope parameter is
    exposed under the "bias" tag, matching the reference's visitor
    (prelu_layer-inl.hpp:61 — Visit("bias", slope, gslope)) so ``bias:lr``
    style hyperparameter scoping applies to it.
    """

    type_names = ("prelu",)
    extra_config_keys = (
        K("init_slope", "float"), K("random_slope", "int", lo=0, hi=1),
        K("random", "float"),
    )

    def __init__(self):
        super().__init__()
        self.init_slope = 0.25
        self.init_random = 0
        self.random = 0.0

    def set_param(self, name, val):
        if name == "init_slope":
            self.init_slope = float(val)
        elif name == "random_slope":
            self.init_random = int(val)
        elif name == "random":
            self.random = float(val)
        else:
            super().set_param(name, val)

    @staticmethod
    def _channel_axis(shape: Shape4) -> int:
        # fc-shaped nodes (n,1,1,d) use the feature axis, conv nodes axis 1
        return 3 if shape[1] == 1 else 1

    def init_params(self, key, in_shapes, dtype=jnp.float32):
        ax = self._channel_axis(in_shapes[0])
        c = in_shapes[0][ax]
        if self.init_random:
            slope = jax.random.uniform(key, (c,), dtype) * self.init_slope
        else:
            slope = jnp.full((c,), self.init_slope, dtype)
        return {"bias": slope}

    def _fn(self, x, ctx):
        raise NotImplementedError  # forward overridden below

    def forward(self, params, buffers, inputs, ctx):
        self.check_n_inputs(inputs, 1)
        x = inputs[0]
        ax = self._channel_axis(x.shape)
        bshape = [1, 1, 1, 1]
        bshape[ax] = x.shape[ax]
        mask = params["bias"].reshape(bshape)
        if ctx.train and self.random > 0:
            u = jax.random.uniform(ctx.next_rng(), x.shape, x.dtype)
            mask = mask * (1 + u * self.random * 2.0 - self.random)
        mask = jnp.clip(mask, 0.0, 1.0)
        out = jnp.where(x > 0, x, x * mask)
        return [out], buffers


class BiasLayer(_UnaryLayer):
    """Self-loop additive per-feature bias for flat nodes
    (bias_layer-inl.hpp:13-82)."""

    type_names = ("bias",)

    def init_params(self, key, in_shapes, dtype=jnp.float32):
        n, c, h, w = in_shapes[0]
        assert c == 1 and h == 1, "bias layer expects a flat (n,1,1,d) node"
        return {"bias": jnp.full((w,), self.param.init_bias, dtype)}

    def forward(self, params, buffers, inputs, ctx):
        self.check_n_inputs(inputs, 1)
        x = inputs[0]
        return [x + params["bias"].reshape(1, 1, 1, -1).astype(x.dtype)], buffers
