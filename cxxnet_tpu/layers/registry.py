"""Layer factory: config type name -> layer instance.

Reference: ``CreateLayer`` / ``GetLayerType`` (``src/layer/layer.h:322-361``,
``layer_impl-inl.hpp:36-76``).  ``pairtest-<master>-<slave>`` composes
recursively (reference encodes it as kPairTestGap*master+slave).  The shared
layer type ``share[tag]`` is resolved by the net builder, not here.
"""

from __future__ import annotations

from typing import Dict, Type

from .activation import (BiasLayer, GeluLayer, InsanityLayer, PReluLayer,
                         ReluLayer, SigmoidLayer, SoftplusLayer, TanhLayer,
                         XeluLayer)
from .base import Layer
from .conv import (AvgPoolingLayer, ConvolutionLayer, InsanityPoolingLayer,
                   LRNLayer, MaxPoolingLayer, ReluMaxPoolingLayer,
                   SumPoolingLayer)
from .fullc import FixConnectLayer, FullConnectLayer
from .loss import L2LossLayer, MultiLogisticLayer, SoftmaxLayer
from .moe import MoELayer
from .norm import BatchNormLayer, DropoutLayer
from .pairtest import PairTestLayer
from .sequence import (AttentionLayer, EmbeddingLayer, LayerNormLayer,
                       SeqFullcLayer, SoftmaxSeqLayer)
from .shape_ops import (ChConcatLayer, ConcatLayer, EltSumLayer, FlattenLayer,
                        MaxoutLayer, SplitLayer)

_REGISTRY: Dict[str, Type[Layer]] = {}


def register(cls: Type[Layer]) -> None:
    for name in cls.type_names:
        _REGISTRY[name] = cls


for _cls in (ReluLayer, SigmoidLayer, TanhLayer, SoftplusLayer, XeluLayer,
             InsanityLayer, PReluLayer, BiasLayer, FullConnectLayer,
             FixConnectLayer, ConvolutionLayer, MaxPoolingLayer,
             ReluMaxPoolingLayer, SumPoolingLayer, AvgPoolingLayer,
             InsanityPoolingLayer, LRNLayer, BatchNormLayer, DropoutLayer,
             FlattenLayer, SplitLayer, ConcatLayer, ChConcatLayer,
             MaxoutLayer, EltSumLayer, SoftmaxLayer, L2LossLayer,
             MultiLogisticLayer, GeluLayer, EmbeddingLayer, LayerNormLayer,
             SeqFullcLayer, AttentionLayer, SoftmaxSeqLayer, MoELayer):
    register(_cls)


def _torch_plugin_factory() -> Layer:
    # plugin layer (caffe-adapter analogue); imported lazily so torch stays
    # off the import path of ordinary runs
    from ..plugin.torch_adapter import TorchLayer
    return TorchLayer()


_REGISTRY["torch"] = _torch_plugin_factory


def layer_type_names():
    return sorted(_REGISTRY)


def create_layer(type_name: str) -> Layer:
    """Create a layer from its config type name."""
    if type_name.startswith("pairtest-"):
        rest = type_name[len("pairtest-"):]
        # reference format: pairtest-<master>-<slave>
        master_name, slave_name = rest.split("-", 1)
        return PairTestLayer(create_layer(master_name), create_layer(slave_name))
    if type_name.startswith("share"):
        raise ValueError("shared layers are resolved by the net builder")
    if type_name not in _REGISTRY:
        raise ValueError(f"unknown layer type: {type_name!r}; "
                         f"known: {layer_type_names()}")
    entry = _REGISTRY[type_name]
    return entry()
