"""TPU-native functional ops used by the layer zoo.

These play the role of mshadow's expression templates (``dot``, ``pool``,
``chpool``, ``unpack_patch2col`` — see reference ``src/layer/*``): instead of
lazily-evaluated CUDA expression trees, each op is a jax/lax function that XLA
fuses and tiles onto the MXU/VPU.  Convolution is ``lax.conv_general_dilated``
(the cuDNN/im2col analogue, reference ``convolution_layer-inl.hpp:70-155``),
pooling is ``lax.reduce_window`` with the reference's tail-window shape rule,
and LRN's cross-channel ``chpool`` is a windowed channel reduction.

All arrays are logical NCHW (batch, channel, y, x), matching the reference's
node layout (``layer.h:34-38``); XLA's layout assignment picks the physical
TPU layout.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..engine import opts

# LRN dispatch (config key pallas_lrn / env CXXNET_PALLAS_LRN).  Default
# "band" (round 4): the channel-window sum as a (C, C) banded matmul on
# the otherwise-idle MXU — beats the round-3 "hwcn" Pallas kernel by
# 1.7 ms/step on AlexNet b1024 (40.10 -> 38.37 device) and needs no
# shape gate.  "hwcn" = the native-layout Pallas kernel (its win region
# below), "1" = legacy (N, C, HW) kernel, "0" = pure XLA chpool.


def _lrn_hwcn_fits(shape) -> bool:
    # empirical win region (v5e): AlexNet's 27x27/13x13 planes win
    # -2.5 ms/step, GoogLeNet's 56x56 planes -4 ms/step (the halo-free
    # untiled kernel; the earlier halo-assembly variant OOM'd VMEM there).
    # Batches must fill the 128-lane tile: Mosaic pads the minor dim to
    # 128 regardless of n, so a small-batch block would be 128/n times
    # larger than the estimate (measured VMEM OOM at n=2) — and the
    # layout-match argument only holds for lane-full batches anyway.
    n, c, h, w = shape
    return (jax.default_backend() == "tpu" and n % 128 == 0
            and w <= 64 and w * c * 128 * 4 <= (3 << 20))


def pool_out_size(in_size: int, ksize: int, stride: int) -> int:
    """Reference pooling output-size rule (pooling_layer-inl.hpp:103-106).

    Includes a clipped tail window when (in-k) is not divisible by stride.
    """
    return min(in_size - ksize + stride - 1, in_size - 1) // stride + 1


def conv_out_size(in_size: int, ksize: int, stride: int, pad: int) -> int:
    """Reference conv output-size rule ((i + 2p - k) / s + 1)."""
    return (in_size + 2 * pad - ksize) // stride + 1


def conv2d(x: jnp.ndarray, w: jnp.ndarray, *, stride: int = 1,
           pad_y: int = 0, pad_x: int = 0, num_group: int = 1,
           ) -> jnp.ndarray:
    """Grouped 2-D convolution, NCHW x OIHW -> NCHW.

    Weight shape (out_c, in_c // num_group, kh, kw); the reference stores the
    equivalent as a 3-D (group, out_c/group, in_c/group*kh*kw) tensor
    (convolution_layer-inl.hpp:29-31).  Accumulates in float32 so bf16 inputs
    still use full-precision MXU accumulation (XLA's default for bf16
    operands on TPU; an explicit preferred_element_type would break the
    conv transpose/grad rule's same-dtype requirement).
    """
    if num_group > 1 and opts.group_conv == "split":
        # A/B probe: grouped conv as per-group convs + concat (XLA's
        # feature_group_count dgrad measured 2.9 ms vs ~1.2 roofline on
        # AlexNet conv2; separate convs give XLA independent layouts)
        cg = x.shape[1] // num_group
        og = w.shape[0] // num_group
        outs = [
            lax.conv_general_dilated(
                lax.slice_in_dim(x, g * cg, (g + 1) * cg, axis=1),
                lax.slice_in_dim(w.astype(x.dtype), g * og, (g + 1) * og,
                                 axis=0),
                window_strides=(stride, stride),
                padding=((pad_y, pad_y), (pad_x, pad_x)),
                dimension_numbers=("NCHW", "OIHW", "NCHW"))
            for g in range(num_group)]
        return jnp.concatenate(outs, axis=1)
    return lax.conv_general_dilated(
        x, w.astype(x.dtype),
        window_strides=(stride, stride),
        padding=((pad_y, pad_y), (pad_x, pad_x)),
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        feature_group_count=num_group,
    )


def conv2d_s2d(x: jnp.ndarray, w: jnp.ndarray, *, stride: int,
               pad_y: int = 0, pad_x: int = 0) -> jnp.ndarray:
    """Space-to-depth convolution: rearrange stride-s spatial blocks into
    channels and run the equivalent stride-1 conv.

    Numerically identical to ``conv2d`` (same contraction, reordered), but
    maps far better onto the MXU for the AlexNet-conv1 shape class (large
    kernel, large stride, few input channels), where the strided access
    pattern and tiny channel dim starve the systolic array.  No reference
    counterpart — this is a TPU-specific lowering choice behind the same
    layer math.
    """
    s = stride
    ci = w.shape[1]
    assert ci == x.shape[1], "conv2d_s2d: grouped conv not supported"
    oh = conv_out_size(x.shape[2], w.shape[2], s, pad_y)
    ow = conv_out_size(x.shape[3], w.shape[3], s, pad_x)
    xb, _, _ = s2d_input(x, s, w.shape[2], w.shape[3], oh, ow, pad_y, pad_x)
    return conv2d_pres2d(xb, w, stride=s)


def s2d_weights(w: jnp.ndarray, s: int) -> jnp.ndarray:
    """(co, ci, kh, kw) -> the dense stride-1 weights (co, ci*s*s, kb_y,
    kb_x) matching ``s2d_input``'s (c, sy, sx) channel order."""
    co, ci, kh, kw = w.shape
    kb_y, kb_x = -(-kh // s), -(-kw // s)
    wp = jnp.pad(w, ((0, 0), (0, 0),
                     (0, kb_y * s - kh), (0, kb_x * s - kw)))
    wb_ = wp.reshape(co, ci, kb_y, s, kb_x, s)
    return wb_.transpose(0, 1, 3, 5, 2, 4).reshape(co, ci * s * s,
                                                   kb_y, kb_x)


def conv2d_pres2d(xb: jnp.ndarray, w: jnp.ndarray, *,
                  stride: int) -> jnp.ndarray:
    """Convolution on an input ALREADY in space-to-depth layout (the
    input-boundary staging path: the batch was transformed once at
    staging, so the step only pays the dense stride-1 conv — and its
    wgrad contracts directly against the staged s2d activation, the
    geometry XLA's dilated wgrad starves on; BASELINE.md round-4 per-op
    table).  ``w`` stays in canonical (co, ci, kh, kw) form — the tiny
    weight-side rearrangement (35 KB for AlexNet conv1) runs in-step and
    autodiff transposes it back, so checkpoints and get/set_weight keep
    the reference layout."""
    return lax.conv_general_dilated(
        xb, s2d_weights(w, stride).astype(xb.dtype), window_strides=(1, 1),
        padding=((0, 0), (0, 0)),
        dimension_numbers=("NCHW", "OIHW", "NCHW"))


def s2d_staged_shape(c: int, stride: int, kh: int, kw: int,
                     oh: int, ow: int) -> Tuple[int, int, int]:
    """Per-image (c', hb, wb) shape of a batch staged by ``s2d_input`` —
    the delivery shape of the ``input_s2d`` pipeline contract (benches
    and host iterators must produce exactly this)."""
    s = stride
    kb_y, kb_x = -(-kh // s), -(-kw // s)
    return (c * s * s, oh - 1 + kb_y, ow - 1 + kb_x)


def s2d_input(x: jnp.ndarray, stride: int, kh: int, kw: int,
              oh: int, ow: int, pad_y: int, pad_x: int):
    """The x-side space-to-depth rearrangement shared by conv2d_s2d and the
    Pallas wgrad kernel: (n, c, h, w) -> (n, c*s*s, hb, wb) with channel
    order (c, sy, sx), matching the weight-side layout above.  Returns
    ``(xb, kb_y, kb_x)``."""
    s = stride
    n, c, h, w = x.shape
    kb_y, kb_x = -(-kh // s), -(-kw // s)  # ceil
    hb, wb = oh - 1 + kb_y, ow - 1 + kb_x
    # pad: requested conv padding, then up to whole blocks; a strided conv
    # may also leave unconsumed tail rows/cols (floor in conv_out_size), so
    # clamp the trailing pad at 0 and slice the block grid to size
    xp = jnp.pad(x, ((0, 0), (0, 0),
                     (pad_y, max(0, hb * s - h - pad_y)),
                     (pad_x, max(0, wb * s - w - pad_x))))
    xp = xp[:, :, :hb * s, :wb * s]
    xb = xp.reshape(n, c, hb, s, wb, s)
    return (xb.transpose(0, 1, 3, 5, 2, 4).reshape(n, c * s * s, hb, wb),
            kb_y, kb_x)


# Weight-grad strategy for the small-cin/large-stride conv geometry
# (AlexNet conv1), where XLA's dilated-dy wgrad starves the MXU (~26%
# efficiency, BASELINE.md): "s2d" (default) computes dW through the
# space-to-depth identity (dense stride-1 inner wgrad, pure XLA);
# "pallas" uses the in-VMEM im2col Pallas kernel (interpret-only for now —
# its minor-dim reshapes are rejected by Mosaic on real TPU); "off" keeps
# XLA's dilated formulation.
# (config key fast_wgrad / env CXXNET_FAST_WGRAD -> engine.opts)


def use_fast_wgrad(cin: int, stride: int, num_group: int) -> bool:
    """The geometry class where XLA's dilated wgrad starves the MXU."""
    import jax
    return (opts.fast_wgrad != "off" and num_group == 1 and stride >= 2
            and cin <= 4 and jax.default_backend() == "tpu")


# grouped-conv lowering: "fgc" (default) XLA feature_group_count;
# "split" lowers each group as its own conv + concat (A/B probe for the
# grouped dgrad cost)
# (config key group_conv / env CXXNET_GROUP_CONV -> engine.opts)


# forward lowering for the fast-wgrad conv class: "conv" (default) XLA
# strided conv; "s2d" routes the forward through the space-to-depth
# identity too (A/B probe; round-2 measured it slower on v5e)
# (config key conv1_fwd / env CXXNET_CONV1_FWD -> engine.opts)


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def conv_bias_fast(x: jnp.ndarray, w: jnp.ndarray, b: jnp.ndarray,
                   stride: int, pad_y: int, pad_x: int) -> jnp.ndarray:
    """conv2d + bias with a Pallas weight/bias-grad backward.

    Forward is the ordinary XLA conv (already fast).  Backward computes
    dW+db in one Pallas kernel (ops.pallas_kernels.conv_wgrad_s2d_pallas)
    and dx through XLA's transposed conv — which XLA dead-code-eliminates
    when the conv sits on the data layer, the AlexNet conv1 case.
    """
    if opts.conv1_fwd == "s2d":
        out = conv2d_s2d(x, w, stride=stride, pad_y=pad_y, pad_x=pad_x)
    else:
        out = conv2d(x, w, stride=stride, pad_y=pad_y, pad_x=pad_x)
    return out + b.astype(out.dtype).reshape(1, -1, 1, 1)


def _conv_bias_fast_fwd(x, w, b, stride, pad_y, pad_x):
    return conv_bias_fast(x, w, b, stride, pad_y, pad_x), (x, w)


def _conv_bias_fast_bwd(stride, pad_y, pad_x, res, dy):
    x, w = res
    co, ci, kh, kw = w.shape
    if opts.fast_wgrad == "hwcn":
        # native-layout Pallas kernel (lane-contraction dots; bias grad
        # rides along) — the round-3 formulation that compiles on real TPU
        from .pallas_kernels import conv_wgrad_hwcn_pallas
        dw, db = conv_wgrad_hwcn_pallas(x, dy, kh=kh, kw=kw, stride=stride,
                                        pad_y=pad_y, pad_x=pad_x)
        dw = dw.astype(w.dtype)
        db = db.astype(w.dtype)
    elif opts.fast_wgrad == "pallas":
        from .pallas_kernels import conv_wgrad_s2d_pallas
        # interpret=True: Mosaic rejects the kernel's minor-dim reshapes on
        # real TPU (see conv_wgrad_s2d_pallas), so this mode is a
        # correctness/debugging path, not a fast one
        dw, db = conv_wgrad_s2d_pallas(x, dy, kh=kh, kw=kw, stride=stride,
                                       pad_y=pad_y, pad_x=pad_x,
                                       interpret=True)
        dw = dw.astype(w.dtype)
        db = db.astype(w.dtype)
    else:  # "s2d": dense stride-1 inner wgrad via the s2d identity
        _, vjp_w = jax.vjp(
            lambda wv: conv2d_s2d(x, wv, stride=stride,
                                  pad_y=pad_y, pad_x=pad_x), w)
        (dw,) = vjp_w(dy)
        db = jnp.sum(dy, axis=(0, 2, 3)).astype(w.dtype)
    _, vjp_x = jax.vjp(
        lambda xv: conv2d(xv, w, stride=stride, pad_y=pad_y, pad_x=pad_x), x)
    (dx,) = vjp_x(dy)
    return dx, dw, db


conv_bias_fast.defvjp(_conv_bias_fast_fwd, _conv_bias_fast_bwd)


def pool_out_size_padded(in_size: int, ksize: int, stride: int,
                         pad: int) -> int:
    """Pool output size with symmetric leading padding (a superset of the
    reference, which has no pool padding; needed for same-size inception
    pool branches).

    Capped so the last window's start ``(o-1)*stride - pad`` still touches a
    real input element — otherwise tail windows lying entirely inside the
    padding would emit -inf (max) / 0 (sum) garbage.
    """
    o = pool_out_size(in_size + 2 * pad, ksize, stride)
    return min(o, (in_size - 1 + pad) // stride + 1)


def _pool_padding(h: int, w: int, kh: int, kw: int, stride: int,
                  pad_y: int, pad_x: int
                  ) -> Tuple[Tuple[int, int], Tuple[int, int]]:
    oh = pool_out_size_padded(h, kh, stride, pad_y)
    ow = pool_out_size_padded(w, kw, stride, pad_x)
    tail_h = max(0, (oh - 1) * stride + kh - h - 2 * pad_y)
    tail_w = max(0, (ow - 1) * stride + kw - w - 2 * pad_x)
    return (pad_y, pad_y + tail_h), (pad_x, pad_x + tail_w)


# max-pool backward dispatch: "sas" (default) uses XLA's select-and-scatter
# (the lax.reduce_window VJP) — gradient goes to ONE maximum per window.
# "eq" opts into the equality-mask VJP below: exact mshadow unpool
# semantics (ties get gradient at EVERY maximum), but ~1.8x slower on v5e
# (95.6ms vs 53.3ms AlexNet b1024 step) because the kx*ky dilate-and-add
# passes materialize instead of fusing.
# (config key pool_bwd / env CXXNET_POOL_BWD -> engine.opts)


def _max_pool_raw(x: jnp.ndarray, ksize_y: int, ksize_x: int, stride: int,
                  pad_y: int, pad_x: int) -> jnp.ndarray:
    pad_h, pad_w = _pool_padding(x.shape[2], x.shape[3], ksize_y, ksize_x,
                                 stride, pad_y, pad_x)
    return lax.reduce_window(
        x, -jnp.inf, lax.max,
        window_dimensions=(1, 1, ksize_y, ksize_x),
        window_strides=(1, 1, stride, stride),
        padding=((0, 0), (0, 0), pad_h, pad_w))


@partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5))
def _max_pool_eq(x: jnp.ndarray, ksize_y: int, ksize_x: int, stride: int,
                 pad_y: int, pad_x: int) -> jnp.ndarray:
    return _max_pool_raw(x, ksize_y, ksize_x, stride, pad_y, pad_x)


def _max_pool_eq_fwd(x, ksize_y, ksize_x, stride, pad_y, pad_x):
    y = _max_pool_raw(x, ksize_y, ksize_x, stride, pad_y, pad_x)
    return y, (x, y)


def _cand_indices(in_size: int, k: int, s: int, pad: int, out_size: int):
    """For each input position a, the candidate window indices covering it:
    w in [ceil((a+pad-k+1)/s), floor((a+pad)/s)] ∩ [0, out_size).  Returns
    (ncand, in_size) index + validity arrays, ncand = ceil(k/s) or fewer."""
    a = np.arange(in_size) + pad
    lo = -(-(a - k + 1) // s)
    hi = np.minimum(a // s, out_size - 1)
    ncand = int(np.max(hi - lo + 1)) if in_size else 0
    idx = np.stack([lo + t for t in range(ncand)])
    valid = (idx >= 0) & (idx <= hi)
    return np.clip(idx, 0, out_size - 1), valid


def _max_pool_eq_bwd_gather(ksize_y, ksize_x, stride, pad_y, pad_x, res, dy):
    """Candidate-gather unpool: same all-ties semantics as _max_pool_eq_bwd,
    but formulated as <= ceil(k/s)^2 static row/column gathers of (y, dy)
    back to the input grid instead of kx*ky dilated pads — each input
    position is covered by at most ceil(k/s)^2 windows, so this reads far
    less than the per-offset formulation when stride < kernel."""
    x, y = res
    n, c, h, w = x.shape
    oh, ow = y.shape[2], y.shape[3]
    iy, vy = _cand_indices(h, ksize_y, stride, pad_y, oh)
    ix, vx = _cand_indices(w, ksize_x, stride, pad_x, ow)
    dx = None
    zero = jnp.zeros((), dy.dtype)
    for t in range(iy.shape[0]):
        y_r = jnp.take(y, jnp.asarray(iy[t]), axis=2)
        dy_r = jnp.take(dy, jnp.asarray(iy[t]), axis=2)
        my = jnp.asarray(vy[t])[None, None, :, None]
        for u in range(ix.shape[0]):
            y_c = jnp.take(y_r, jnp.asarray(ix[u]), axis=3)
            dy_c = jnp.take(dy_r, jnp.asarray(ix[u]), axis=3)
            m = my & jnp.asarray(vx[u])[None, None, None, :]
            contrib = jnp.where(m & (x == y_c), dy_c, zero)
            dx = contrib if dx is None else dx + contrib
    return (dx,)


def _max_pool_eq_bwd(ksize_y, ksize_x, stride, pad_y, pad_x, res, dy):
    """Equality-mask max-pool backward (mshadow ``unpool<red::maximum>``
    semantics: every input equal to its window's max receives the window's
    gradient — ties propagate to ALL maxima, unlike XLA select-and-scatter
    which picks one).  Two formulations, picked by CXXNET_POOL_BWD:
    "eq" = kx*ky dilate-and-add passes (measured ~1.8x slower than SAS in
    a full AlexNet step on v5e: the pads materialize); "gather" =
    candidate-window gathers (_max_pool_eq_bwd_gather)."""
    if opts.pool_bwd == "gather":
        return _max_pool_eq_bwd_gather(ksize_y, ksize_x, stride,
                                       pad_y, pad_x, res, dy)
    x, y = res
    n, c, h, w = x.shape
    oh, ow = y.shape[2], y.shape[3]
    s = stride
    (plo_h, phi_h), (plo_w, phi_w) = _pool_padding(
        h, w, ksize_y, ksize_x, stride, pad_y, pad_x)
    H, W = h + plo_h + phi_h, w + plo_w + phi_w
    xp = jnp.pad(x, ((0, 0), (0, 0), (plo_h, phi_h), (plo_w, phi_w)),
                 constant_values=-jnp.inf)
    ext_h, ext_w = (oh - 1) * s + 1, (ow - 1) * s + 1
    acc = None
    zero = jnp.zeros((), x.dtype)
    for i in range(ksize_y):
        for j in range(ksize_x):
            xs = lax.slice(xp, (0, 0, i, j),
                           (n, c, i + ext_h, j + ext_w), (1, 1, s, s))
            contrib = jnp.where(xs == y, dy, zero)
            # dilate back onto the padded input grid at offset (i, j)
            placed = lax.pad(
                contrib, zero,
                ((0, 0, 0), (0, 0, 0),
                 (i, H - i - ext_h, s - 1), (j, W - j - ext_w, s - 1)))
            acc = placed if acc is None else acc + placed
    dx = lax.slice(acc, (0, 0, plo_h, plo_w), (n, c, plo_h + h, plo_w + w))
    return (dx,)


_max_pool_eq.defvjp(_max_pool_eq_fwd, _max_pool_eq_bwd)


# pool layout: "chwn" transposes NCHW -> (C, H, W, N) around the
# reduce_window / select-and-scatter pair.  Measured standalone on v5e
# (AlexNet pool1, b1024): fwd 0.99ms vs 2.93 NCHW, SAS bwd 5.06 vs 8.47 —
# XLA tiles the windowed ops far better with batch minor; whether the
# transposes get absorbed in a full step is measured via fb.py.
# (config key pool_layout / env CXXNET_POOL_LAYOUT -> engine.opts)


def _max_pool_dispatch(x, ksize_y, ksize_x, stride, pad_y, pad_x):
    if opts.pool_bwd in ("eq", "gather"):
        return _max_pool_eq(x, ksize_y, ksize_x, stride, pad_y, pad_x)
    return _max_pool_raw(x, ksize_y, ksize_x, stride, pad_y, pad_x)


def _hwcn_pool_ok(x, ksize_y: int, ksize_x: int, stride: int,
                  pad_y: int, pad_x: int) -> bool:
    """Shapes the native-layout (H, W, C, N) Pallas pool kernels serve
    on TPU — the ONE eligibility gate shared by ``max_pool2d`` and the
    relu-fused ``max_pool2d_relu``, so the two entry points can never
    accept different shapes (which would flip a pool between all-ties
    and SAS gradient semantics depending on the call site)."""
    from .pallas_kernels import max_pool_hwcn_supported
    return (pad_y == 0 and pad_x == 0 and ksize_y == ksize_x
            and jax.default_backend() == "tpu"
            and x.shape[0] % 128 == 0
            and max_pool_hwcn_supported(x.shape, stride))


def max_pool2d(x: jnp.ndarray, ksize_y: int, ksize_x: int, stride: int,
               pad_y: int = 0, pad_x: int = 0) -> jnp.ndarray:
    hwcn_ok = _hwcn_pool_ok(x, ksize_y, ksize_x, stride, pad_y, pad_x)
    # "auto": Pallas all-ties where the hwcn kernel takes the shape, SAS
    # elsewhere (measured ~equal to pure SAS on the GoogLeNet stage pools,
    # BASELINE.md round 5).  Gradient SEMANTICS then vary per pool
    # (all-ties vs one-winner at ties) — an explicit opt-in, never the
    # default.
    want_allties = (opts.pool_layout == "hwcn"
                    or opts.pool_bwd in ("eq", "gather", "auto"))
    if want_allties and hwcn_ok:
        # Pallas kernels in XLA's native (H, W, C, N) activation layout:
        # exact mshadow all-ties backward, ~15x faster than the XLA
        # dilate-and-add eq formulation (6 vs 96 ms standalone on AlexNet
        # pool1 b1024; still slower than SAS, so an exactness opt-in)
        from .pallas_kernels import max_pool_hwcn
        return max_pool_hwcn(x, ksize_y, stride)
    if opts.pool_layout == "hwcn" and not hwcn_ok:
        # keep all-ties semantics for the shapes the kernel can't take
        # (padded pools, partial batches, CPU) — gradient semantics must
        # not flip with batch divisibility mid-run
        return _max_pool_eq(x, ksize_y, ksize_x, stride, pad_y, pad_x)
    # ("auto" reaching this line means the Pallas kernel declined the
    # shape, so the lowering IS SAS — honor the chwn layout choice)
    if opts.pool_layout == "chwn" and opts.pool_bwd in ("sas", "auto"):
        xt = jnp.transpose(x, (1, 2, 3, 0))
        # reuse the NCHW padding/window logic by viewing (C, H, W, N) as
        # (N', C', H, W) with batch'=C and channel'=H: reduce_window only
        # cares about which axes carry windows
        yt = _pool_nchw_as_chwn(xt, ksize_y, ksize_x, stride, pad_y, pad_x)
        return jnp.transpose(yt, (3, 0, 1, 2))
    return _max_pool_dispatch(x, ksize_y, ksize_x, stride, pad_y, pad_x)


def max_pool2d_relu(x: jnp.ndarray, ksize_y: int, ksize_x: int,
                    stride: int, pad_y: int = 0, pad_x: int = 0
                    ) -> jnp.ndarray:
    """``relu(max_pool2d(x))`` — the deferred-relu pool (the
    ``pool_relu_reorder`` peephole's execution form).  With
    ``pool_relu_fuse = 1`` and a shape the hwcn Pallas kernel takes,
    the relu backward fuses into the multi-row all-ties unpool kernel
    (``pallas_kernels.max_pool_relu_hwcn``) — the separate relu-bwd
    pass over the pooled tensor disappears.  Fusing implies the
    all-ties backward for that pool (like ``pool_bwd = auto``); the
    unfused fallback keeps today's exact pair: the configured pool
    backward followed by the ``relu_vjp``-configured relu."""
    if opts.pool_relu_fuse == "1" \
            and _hwcn_pool_ok(x, ksize_y, ksize_x, stride, pad_y, pad_x):
        from .pallas_kernels import max_pool_relu_hwcn
        return max_pool_relu_hwcn(x, ksize_y, stride)
    from ..layers.activation import apply_relu
    return apply_relu(max_pool2d(x, ksize_y, ksize_x, stride, pad_y, pad_x))


def _pool_nchw_as_chwn(xt, ksize_y, ksize_x, stride, pad_y, pad_x):
    """Max pool over dims (1, 2) of a (C, H, W, N) array with the
    reference tail-window rule."""
    pad_h, pad_w = _pool_padding(xt.shape[1], xt.shape[2], ksize_y,
                                 ksize_x, stride, pad_y, pad_x)
    return lax.reduce_window(
        xt, -jnp.inf, lax.max,
        window_dimensions=(1, ksize_y, ksize_x, 1),
        window_strides=(1, stride, stride, 1),
        padding=((0, 0), pad_h, pad_w, (0, 0)))


def sum_pool2d(x: jnp.ndarray, ksize_y: int, ksize_x: int, stride: int,
               pad_y: int = 0, pad_x: int = 0) -> jnp.ndarray:
    pad_h, pad_w = _pool_padding(x.shape[2], x.shape[3], ksize_y, ksize_x,
                                 stride, pad_y, pad_x)
    return lax.reduce_window(
        x, 0.0, lax.add,
        window_dimensions=(1, 1, ksize_y, ksize_x),
        window_strides=(1, 1, stride, stride),
        padding=((0, 0), (0, 0), pad_h, pad_w))


def avg_pool2d(x: jnp.ndarray, ksize_y: int, ksize_x: int, stride: int,
               pad_y: int = 0, pad_x: int = 0) -> jnp.ndarray:
    """Average pooling; divides by the *full* kernel size even for clipped
    tail windows / padding, matching the reference
    (pooling_layer-inl.hpp:47-53)."""
    s = sum_pool2d(x, ksize_y, ksize_x, stride, pad_y, pad_x)
    return s * jnp.array(1.0 / (ksize_y * ksize_x), x.dtype)


def jitter5(x: jnp.ndarray, mask: jnp.ndarray, p_keep: float) -> jnp.ndarray:
    """Stochastic neighbor redirect (insanity_pooling_layer-inl.hpp:70-93).

    Per position, ``mask`` (uniform [0,1), same shape as x) picks one of five
    sources with band boundaries p, p+d, p+2d, p+3d (d = (1-p)/4): the
    position itself, or its y-1 / y+1 / x-1 / x+1 neighbor, edge-clamped.
    Returns the jittered image xj with xj[y,x] = x[loc_y, loc_x].
    """
    d = (1.0 - p_keep) / 4.0
    up = jnp.concatenate([x[:, :, :1], x[:, :, :-1]], axis=2)      # x[y-1]
    down = jnp.concatenate([x[:, :, 1:], x[:, :, -1:]], axis=2)    # x[y+1]
    left = jnp.concatenate([x[:, :, :, :1], x[:, :, :, :-1]], axis=3)
    right = jnp.concatenate([x[:, :, :, 1:], x[:, :, :, -1:]], axis=3)
    return jnp.where(mask < p_keep, x,
           jnp.where(mask < p_keep + d, up,
           jnp.where(mask < p_keep + 2 * d, down,
           jnp.where(mask < p_keep + 3 * d, left, right))))


def insanity_max_pool(x: jnp.ndarray, mask: jnp.ndarray, ksize_y: int,
                      ksize_x: int, stride: int, p_keep: float) -> jnp.ndarray:
    """Train-time insanity pooling, exact reference semantics
    (insanity_pooling_layer-inl.hpp:13-49 forward, :150-210 backward).

    Forward: max over the window of the JITTERED image (each (y,x) read is
    redirected by the mask — the same redirect for every window covering it).
    Backward: the reference's insanity_unpool propagates the pooled gradient
    to the *window position* (y,x) whenever its jittered value equals the
    window max (``Reducer::PartialGrad`` — ALL ties receive gradient), NOT
    through the jitter gather; the straight-through term below reproduces
    exactly that: value is xj, gradient w.r.t. x is the eq-mask unpool of xj
    assigned at-position.
    """
    xj = jitter5(x, mask, p_keep)
    xj = x + lax.stop_gradient(xj - x)
    return _max_pool_eq(xj, ksize_y, ksize_x, stride, 0, 0)


def chpool_sum(x: jnp.ndarray, nsize: int) -> jnp.ndarray:
    """Cross-channel windowed sum (mshadow ``chpool<red::sum>``), centered
    window of width ``nsize`` over the channel axis of NCHW.

    Implemented as nsize shifted-slice adds rather than ``reduce_window``:
    the window sits on the non-minor channel axis where reduce_window tiles
    poorly on TPU, while shifted adds fuse into one elementwise pass."""
    lo = nsize // 2
    hi = nsize - 1 - lo
    c = x.shape[1]
    xp = jnp.pad(x, ((0, 0), (lo, hi), (0, 0), (0, 0)))
    out = xp[:, 0:c]
    for i in range(1, nsize):
        out = out + xp[:, i:i + c]
    return out


def lrn(x: jnp.ndarray, nsize: int, alpha: float, beta: float, knorm: float
        ) -> jnp.ndarray:
    """Local response normalization across channels
    (reference lrn_layer-inl.hpp:53-56): out = x * (k + a/n * sum x^2)^-b."""
    if opts.pallas_lrn == "1":
        from .pallas_kernels import lrn_pallas
        return lrn_pallas(x, nsize, alpha, beta, knorm)
    if opts.pallas_lrn == "hwcn" and _lrn_hwcn_fits(x.shape):
        # round-3 kernel in XLA's native (H, W, C, N) activation layout —
        # superseded as default by the banded-matmul form (round 4:
        # 40.10 -> 38.37 ms/step on AlexNet b1024)
        from .pallas_kernels import lrn_pallas_hwcn
        return lrn_pallas_hwcn(x, nsize, alpha, beta, knorm)
    if opts.pallas_lrn == "band":
        # default: the channel-window sum as a (C, C) banded matmul on
        # the (otherwise idle) MXU; autodiff gives the transposed-band
        # backward.  Pure XLA — no shape gate needed
        return lrn_band(x, nsize, alpha, beta, knorm)
    if opts.pallas_lrn == "bandconv":
        # same banded contraction expressed as a 1x1 conv: the einsum
        # form contracts over C (the SUBLANE dim), which costs a
        # (n<->c) relayout transpose on large planes (measured 0.95
        # ms/step on GoogLeNet's 56^2x192 LRN); the conv emitter reads
        # the native {0,1,3,2} activation layout directly
        return lrn_band(x, nsize, alpha, beta, knorm, via_conv=True)
    salpha = alpha / nsize
    norm = chpool_sum(jnp.square(x), nsize) * salpha + knorm
    if beta == 0.75:
        # norm^-0.75 == rsqrt(norm * sqrt(norm)): two sqrt-family VPU ops
        # instead of a transcendental pow (exp∘log)
        return x * lax.rsqrt(norm * lax.sqrt(norm))
    return x * jnp.power(norm, -beta)


def lrn_band(x: jnp.ndarray, nsize: int, alpha: float, beta: float,
             knorm: float, via_conv: bool = False) -> jnp.ndarray:
    """LRN with the cross-channel window sum as a BANDED MATMUL.

    The channel-window reduction is a (C, C) band-matrix contraction —
    one tiny MXU matmul per spatial position batch instead of nsize
    shifted VPU adds, and the MXU is idle during LRN anyway.  Autodiff
    produces the backward as the transposed band matmul, so fwd+bwd both
    ride the MXU with no custom VJP.  Numerically identical to the
    chpool formulation (same clipped window; tests compare against it).
    """
    c = x.shape[1]
    lo = nsize // 2
    hi = nsize - 1 - lo
    i = jnp.arange(c)
    # out channel d sums input channels [d-lo, d+hi], i.e. d - c in
    # [-hi, lo]  (matches chpool_sum; asymmetric for even nsize)
    band = ((i[None, :] - i[:, None] >= -hi)
            & (i[None, :] - i[:, None] <= lo)).astype(x.dtype)
    sq = jnp.square(x)
    # HIGHEST: keep the f32 path exact on the MXU (bf16 inputs are
    # unaffected — they already accumulate in f32)
    if via_conv:
        # out channel d = sum_c band[c, d] * sq[:, c]: weight (d, c, 1, 1)
        w = band.T.reshape(c, c, 1, 1)
        summed = lax.conv_general_dilated(
            sq, w, window_strides=(1, 1), padding="VALID",
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
            precision=lax.Precision.HIGHEST)
        norm = summed * (alpha / nsize) + knorm
    else:
        norm = (jnp.einsum("nchw,cd->ndhw", sq, band,
                           precision=lax.Precision.HIGHEST)
                * (alpha / nsize) + knorm)
    if beta == 0.75:
        return x * lax.rsqrt(norm * lax.sqrt(norm))
    return x * jnp.power(norm, -beta)


def softmax(x: jnp.ndarray) -> jnp.ndarray:
    return jax.nn.softmax(x, axis=-1)


def log_softmax(x: jnp.ndarray) -> jnp.ndarray:
    return jax.nn.log_softmax(x, axis=-1)


def dropout_mask(key: jax.Array, shape, pkeep: float, dtype=jnp.float32
                 ) -> jnp.ndarray:
    """Reference dropout mask: threshold(uniform, pkeep) / pkeep
    (dropout_layer-inl.hpp:46-48)."""
    u = jax.random.uniform(key, shape, dtype)
    return (u < pkeep).astype(dtype) * (1.0 / pkeep)
