"""Hand-written Pallas TPU kernels for ops XLA tiles poorly.

The reference proves its op set is user-extensible at the expression level
(``insanity_pooling_layer-inl.hpp:13-49`` defines custom mshadow expressions
in-tree); the TPU analogue is this module: custom Pallas kernels slotted in
behind the same op signatures as the XLA path.

First resident: **LRN** (``lrn_layer-inl.hpp:53-76``).  The cross-channel
windowed reduction sits on a non-minor axis, so the XLA path materialises a
``chpool`` intermediate between two elementwise passes over HBM.  The Pallas
kernel does square → windowed channel sum → normalise in one VMEM-resident
pass per batch row (forward), and the full hand-derived backward

    dx = g·norm^{-β} − 2βα/n · x · chpool(g · x · norm^{-β-1})

in a second single-pass kernel via ``jax.custom_vjp``.

Kernels run in interpreter mode off-TPU so the same code path is unit-tested
on the CPU mesh (pallas_guide: ``interpret=True``).

Round-2 measured the (N, C, HW)-layout kernel losing in-step to XLA: a
pallas_call on a logical-NCHW activation forces a relayout (XLA keeps conv
activations physically (H, W, C-sublane, N-lane), batch minor).  Round 3's
``lrn_pallas_hwcn`` transposes to the MATCHING logical order first — the
boundary becomes a bitcast — and wins ~2 ms/step on the AlexNet b1024
config, so it is the default dispatch for lane-full batches in its
measured win region (``CXXNET_PALLAS_LRN``: "hwcn" (default) / "1" legacy
(N, C, HW) kernel / "0" pure XLA; see ``nn.lrn``).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu import fails on some CPU-only builds; interpret mode needs none
    from jax.experimental.pallas import tpu as pltpu
    _VMEM = pltpu.VMEM
except ImportError:  # pragma: no cover
    pltpu = None
    _VMEM = None


def _on_tpu() -> bool:
    return jax.default_backend() == "tpu"


def _block_spec(nb: int, c: int, hw: int):
    """(NB, C, HW) batch-tile per grid step, resident in VMEM.  NB > 1
    matters: one-row blocks ran 1024 programs per call on AlexNet shapes and
    the per-program overhead swamped the kernel."""
    if _VMEM is None:
        return pl.BlockSpec((nb, c, hw), lambda i: (i, 0, 0))
    return pl.BlockSpec((nb, c, hw), lambda i: (i, 0, 0), memory_space=_VMEM)


def _chwin_sum(sq: jnp.ndarray, nsize: int,
               transpose: bool = False) -> jnp.ndarray:
    """Windowed sum over axis 1 (channels) of an (NB, C, HW) block: element
    j sums sq[j-lo .. j+hi] with lo = nsize//2, hi = nsize-1-lo —
    ``chpool_sum``'s window placement.  ``transpose=True`` swaps lo/hi,
    giving the adjoint window needed by the backward pass for even nsize."""
    c = sq.shape[1]
    lo = nsize // 2
    hi = nsize - 1 - lo
    if transpose:
        lo, hi = hi, lo
    zshape = list(sq.shape)
    acc = sq
    for off in range(1, hi + 1):  # channels above j
        zshape[1] = off
        acc = acc + jnp.concatenate(
            [sq[:, off:], jnp.zeros(zshape, sq.dtype)], axis=1)
    for off in range(1, lo + 1):  # channels below j
        zshape[1] = off
        acc = acc + jnp.concatenate(
            [jnp.zeros(zshape, sq.dtype), sq[:, :c - off]], axis=1)
    return acc


def _norm_pow(norm: jnp.ndarray, beta: float) -> jnp.ndarray:
    """norm^-beta; rsqrt-family fast path for the canonical beta=0.75."""
    if beta == 0.75:
        return jax.lax.rsqrt(norm * jax.lax.sqrt(norm))
    return jnp.power(norm, -beta)


def _lrn_fwd_kernel(x_ref, o_ref, *, nsize, salpha, beta, knorm):
    x = x_ref[...].astype(jnp.float32)
    norm = _chwin_sum(x * x, nsize) * salpha + knorm
    o_ref[...] = (x * _norm_pow(norm, beta)).astype(o_ref.dtype)


def _lrn_bwd_kernel(x_ref, g_ref, dx_ref, *, nsize, salpha, beta, knorm):
    x = x_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32)
    norm = _chwin_sum(x * x, nsize) * salpha + knorm
    npow = _norm_pow(norm, beta)              # norm^-b
    inner = g * x * (npow / norm)             # g x norm^{-b-1}
    dx = g * npow - (2.0 * beta * salpha) * x * _chwin_sum(
        inner, nsize, transpose=True)
    dx_ref[...] = dx.astype(dx_ref.dtype)


def _lrn_batch_tile(n: int, c: int, hw: int, itemsize: int) -> int:
    """Largest batch tile dividing n with a ~1MB input block: the backward
    kernel holds ~6 f32 block-sized temporaries plus the in/out blocks, so
    a bigger block blows the 16MB scoped-vmem limit."""
    nb = max(1, (1 << 20) // max(c * hw * itemsize, 1))
    while n % nb != 0:
        nb -= 1
    return nb


def _call_per_batch(kernel, out_dtype, nsize, salpha, beta, knorm, *args3d,
                    interpret):
    n, c, hw = args3d[0].shape
    nb = _lrn_batch_tile(n, c, hw, args3d[0].dtype.itemsize)
    kern = functools.partial(kernel, nsize=nsize, salpha=salpha, beta=beta,
                             knorm=knorm)
    return pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct((n, c, hw), out_dtype),
        grid=(n // nb,),
        in_specs=[_block_spec(nb, c, hw) for _ in args3d],
        out_specs=_block_spec(nb, c, hw),
        interpret=interpret,
    )(*args3d)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4))
def lrn_pallas(x: jnp.ndarray, nsize: int, alpha: float, beta: float,
               knorm: float) -> jnp.ndarray:
    """LRN over NCHW via the Pallas kernel (same semantics as ``nn.lrn``)."""
    out, _ = _lrn_fwd_res(x, nsize, alpha, beta, knorm)
    return out


def _lrn_fwd_res(x, nsize, alpha, beta, knorm):
    n, c, h, w = x.shape
    x3 = x.reshape(n, c, h * w)
    out = _call_per_batch(_lrn_fwd_kernel, x.dtype, nsize, alpha / nsize,
                          beta, knorm, x3, interpret=not _on_tpu())
    return out.reshape(n, c, h, w), x


def _lrn_bwd_res(nsize, alpha, beta, knorm, res, g):
    x = res
    n, c, h, w = x.shape
    dx = _call_per_batch(_lrn_bwd_kernel, x.dtype, nsize, alpha / nsize,
                         beta, knorm, x.reshape(n, c, h * w),
                         g.reshape(n, c, h * w), interpret=not _on_tpu())
    return (dx.reshape(n, c, h, w),)


lrn_pallas.defvjp(_lrn_fwd_res, _lrn_bwd_res)


# --------------------------------------------------------------------------
# LRN in XLA's native activation layout.  Profiling the AlexNet step shows
# XLA lays conv activations out as {0,1,3,2:T(8,128)} — physically
# (H, W, C-sublane, N-lane), batch minor.  A pallas_call on the logical
# NCHW array therefore forces a relayout (the round-2 kernel's measured
# boundary toll); transposing to the MATCHING logical order (H, W, C, N)
# first makes the transpose a layout-change XLA can satisfy with a bitcast,
# and inside the kernel the channel window sits on the sublane axis where
# shifted slices are natively supported (experiments/mosaic_probe2.py).


def _halo_concat(center, lo_v, hi_v, bc, nblk, halo):
    """Assemble the C-extended block: ``halo`` channels from each
    neighbouring C-block, zero-masked at the array edges (LRN zero-pads).
    The halo refs are 8-wide (sublane tile minimum); only the adjacent
    ``halo`` channels are used."""
    if not halo:
        return center
    parts = [jnp.where(bc > 0, lo_v[:, :, lo_v.shape[2] - halo:], 0.0),
             center,
             jnp.where(bc < nblk - 1, hi_v[:, :, :halo], 0.0)]
    return jnp.concatenate(parts, axis=2)


def _cshift(v, i):
    """v shifted by i channels (axis 2), zero-filled (concat form —
    Mosaic-safe)."""
    if i == 0:
        return v
    z = jnp.zeros(v.shape[:2] + (abs(i),) + v.shape[3:], v.dtype)
    if i > 0:
        return jnp.concatenate([v[:, :, i:], z], axis=2)
    return jnp.concatenate([z, v[:, :, :i]], axis=2)


def _lrn_hwcn_fwd_kernel(x_ref, xlo_ref, xhi_ref, o_ref, *, nsize, salpha,
                         beta, knorm, halo):
    bc = pl.program_id(1)
    nblk = pl.num_programs(1)
    lo = nsize // 2
    hi = nsize - 1 - lo
    x = x_ref[...].astype(jnp.float32)        # (HB, W, CB, NB)
    cb = x.shape[2]
    xe = _halo_concat(x, xlo_ref[...].astype(jnp.float32),
                      xhi_ref[...].astype(jnp.float32), bc, nblk, halo)
    sq = xe * xe
    # center channel j = extended channel halo + j; window [j-lo, j+hi]
    acc = None
    for i in range(nsize):
        if halo:
            sl = sq[:, :, halo - lo + i:halo - lo + i + cb]
        else:  # untiled: zero-fill shifts instead of halo slices
            sl = _cshift(sq, i - lo)
        acc = sl if acc is None else acc + sl
    norm = acc * salpha + knorm
    o_ref[...] = (x * _norm_pow(norm, beta)).astype(o_ref.dtype)


def _lrn_hwcn_bwd_kernel(x_ref, xlo_ref, xhi_ref, g_ref, glo_ref, ghi_ref,
                         dx_ref, *, nsize, salpha, beta, knorm, halo):
    bc = pl.program_id(1)
    nblk = pl.num_programs(1)
    lo = nsize // 2
    hi = nsize - 1 - lo
    x = x_ref[...].astype(jnp.float32)
    cb = x.shape[2]
    xe = _halo_concat(x, xlo_ref[...].astype(jnp.float32),
                      xhi_ref[...].astype(jnp.float32), bc, nblk, halo)
    ge = _halo_concat(g_ref[...].astype(jnp.float32),
                      glo_ref[...].astype(jnp.float32),
                      ghi_ref[...].astype(jnp.float32), bc, nblk, halo)
    # norm on the extended block: valid wherever the window stays inside
    # it — true for all channels the adjoint sum below touches, because
    # halo >= lo + hi (edge zero-fill is the correct array-edge padding)
    sq = xe * xe
    norm_e = None
    for i in range(-lo, hi + 1):
        sl = _cshift(sq, i)
        norm_e = sl if norm_e is None else norm_e + sl
    norm_e = norm_e * salpha + knorm
    npow_e = _norm_pow(norm_e, beta)
    inner_e = ge * xe * (npow_e / norm_e)
    x_c = xe[:, :, halo:halo + cb]
    g_c = ge[:, :, halo:halo + cb]
    npow_c = npow_e[:, :, halo:halo + cb]
    # adjoint window swaps lo/hi: dx[j] -= 2ba x[j] sum_{i in [-hi, lo]}
    # inner[j+i]
    wsum = None
    for i in range(-hi, lo + 1):
        if halo:
            sl = inner_e[:, :, halo + i:halo + i + cb]
        else:
            sl = _cshift(inner_e, i)
        wsum = sl if wsum is None else wsum + sl
    dx = g_c * npow_c - (2.0 * beta * salpha) * x_c * wsum
    dx_ref[...] = dx.astype(dx_ref.dtype)




def _lrn_hwcn_fwd_kernel_u(x_ref, o_ref, *, nsize, salpha, beta, knorm):
    lo = nsize // 2
    hi = nsize - 1 - lo
    x = x_ref[...].astype(jnp.float32)        # (HB, W, C, NB)
    sq = x * x
    acc = None
    for i in range(-lo, hi + 1):
        sl = _cshift(sq, i)
        acc = sl if acc is None else acc + sl
    norm = acc * salpha + knorm
    o_ref[...] = (x * _norm_pow(norm, beta)).astype(o_ref.dtype)


def _lrn_hwcn_bwd_kernel_u(x_ref, g_ref, dx_ref, *, nsize, salpha, beta,
                           knorm):
    lo = nsize // 2
    hi = nsize - 1 - lo
    x = x_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32)
    sq = x * x
    norm = None
    for i in range(-lo, hi + 1):
        sl = _cshift(sq, i)
        norm = sl if norm is None else norm + sl
    norm = norm * salpha + knorm
    npow = _norm_pow(norm, beta)
    inner = g * x * (npow / norm)
    wsum = None
    for i in range(-hi, lo + 1):
        sl = _cshift(inner, i)
        wsum = sl if wsum is None else wsum + sl
    dx = g * npow - (2.0 * beta * salpha) * x * wsum
    dx_ref[...] = dx.astype(dx_ref.dtype)

# per-program VMEM budget for the LRN block planner: the round-3
# "measured-working" 3 MB leaves AlexNet's odd 27-row planes at hb=1
# (216 tiny programs); raced values recorded in BASELINE.md
_LRN_BUDGET = 3 << 20


def _lrn_hwcn_call(kernel, out_dtype, nsize, salpha, beta, knorm, args,
                   interpret):
    h, w, c, n = args[0].shape
    lo = nsize // 2
    hi = nsize - 1 - lo
    # bwd recomputes norms for halo channels, whose windows reach another
    # lo+hi channels out — one halo width serves both kernels
    halo = max(lo + hi, 1)
    nb = 128 if n % 128 == 0 else n
    # C-tile (halo channels from neighbour-block refs, zero-masked at the
    # edges) only when the untiled per-block working set is too large;
    # the untiled path skips the halo assembly entirely (fewer VMEM
    # temporaries — measured: the AlexNet shapes prefer 2-row untiled
    # blocks, GoogLeNet's 56x56 shapes need the C-tiling)
    cb = c
    while cb > 2 * halo and w * cb * nb * 4 > _LRN_BUDGET:
        cb //= 2
    while c % cb:
        cb -= 1
    hblk = 8  # halo refs are one sublane tile wide (>= any lo+hi here)
    assert halo <= hblk, f"lrn nsize {nsize} halo {halo} exceeds tile"
    if cb % hblk or cb < hblk:
        cb = c  # halo-block indexing needs 8 | cb; fall back to whole C
    nblk = c // cb
    untiled = nblk == 1
    if untiled:
        halo = 0  # no neighbours: no halo refs, no extended temps
        kernel = {_lrn_hwcn_fwd_kernel: _lrn_hwcn_fwd_kernel_u,
                  _lrn_hwcn_bwd_kernel: _lrn_hwcn_bwd_kernel_u}[kernel]
    plane = w * (cb + 2 * halo) * nb * 4
    hb = max(1, _LRN_BUDGET // max(plane, 1))
    while h % hb:
        hb -= 1
    kern = functools.partial(kernel, nsize=nsize, salpha=salpha, beta=beta,
                             knorm=knorm,
                             **({} if untiled else {"halo": halo}))
    kw = {} if _VMEM is None else {"memory_space": _VMEM}
    spec = pl.BlockSpec((hb, w, cb, nb),
                        lambda i, j, k: (i, 0, j, k), **kw)
    lo_spec = pl.BlockSpec(
        (hb, w, hblk, nb),
        lambda i, j, k: (i, 0, jnp.maximum(j * (cb // hblk) - 1, 0), k),
        **kw)
    hi_spec = pl.BlockSpec(
        (hb, w, hblk, nb),
        lambda i, j, k: (i, 0, jnp.minimum((j + 1) * (cb // hblk),
                                           c // hblk - 1), k),
        **kw)
    per_arg = [spec] if untiled else [spec, lo_spec, hi_spec]
    return pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct((h, w, c, n), out_dtype),
        grid=(h // hb, nblk, n // nb),
        in_specs=per_arg * len(args),
        out_specs=spec,
        interpret=interpret,
    )(*[a for a in args for _ in range(len(per_arg))])


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4))
def lrn_pallas_hwcn(x: jnp.ndarray, nsize: int, alpha: float, beta: float,
                    knorm: float) -> jnp.ndarray:
    """LRN over logical NCHW via an (H, W, C, N)-layout Pallas kernel.

    The wrapping transposes match XLA's physical activation layout, so
    they lower to bitcasts rather than data movement (see module note).
    """
    out, _ = _lrn_hwcn_fwd_res(x, nsize, alpha, beta, knorm)
    return out


def _lrn_hwcn_fwd_res(x, nsize, alpha, beta, knorm):
    xt = jnp.transpose(x, (2, 3, 1, 0))       # (H, W, C, N)
    out = _lrn_hwcn_call(_lrn_hwcn_fwd_kernel, x.dtype, nsize,
                         alpha / nsize, beta, knorm, (xt,),
                         interpret=not _on_tpu())
    return jnp.transpose(out, (3, 2, 0, 1)), x


def _lrn_hwcn_bwd_res(nsize, alpha, beta, knorm, res, g):
    x = res
    xt = jnp.transpose(x, (2, 3, 1, 0))
    gt = jnp.transpose(g, (2, 3, 1, 0))
    dx = _lrn_hwcn_call(_lrn_hwcn_bwd_kernel, x.dtype, nsize,
                        alpha / nsize, beta, knorm, (xt, gt),
                        interpret=not _on_tpu())
    return (jnp.transpose(dx, (3, 2, 0, 1)),)


lrn_pallas_hwcn.defvjp(_lrn_hwcn_fwd_res, _lrn_hwcn_bwd_res)


# VMEM budget for the multi-row backward's channel tile: estimates over
# ~13.4 MB crashed the Mosaic compile (GoogLeNet c832/w14, c480/w32)
_MR_BWD_VMEM_CAP = 12 << 20


def _pick_cb(c: int, per_cb_bytes: int, cap: int) -> int:
    """Largest channel tile dividing c that fits the VMEM budget, else the
    smallest legal tile.  Mosaic requires a block dim be a multiple of 8
    OR the full array dim — the old halving loop could land on e.g. 60
    for c=480 (GoogLeNet stage-3 pool), which is neither, and failed TPU
    compilation."""
    legal = [cb for cb in range(1, c + 1)
             if c % cb == 0 and (cb == c or cb % 8 == 0)]
    return next((cb for cb in reversed(legal)
                 if cb * per_cb_bytes <= cap), legal[0])


def _mp_mr_plan(c: int, w: int, nb: int, s: int, hb: int = None):
    """Tile plan for the MULTI-ROW pool backward, shared by the shape gate
    (:func:`max_pool_hwcn_supported`) and the kernel launcher
    (:func:`_mp_hwcn_bwd`) so the two can't silently diverge: returns
    ``(hb, cb, per_cb_bytes)``.

    * ``hb`` — input rows per program; default 3*s (amortizes per-program
      overhead), rounded down to a multiple of s (static candidate-row
      offsets require s | hb).
    * ``per_cb_bytes`` — dominant VMEM per (w, cb, nb) plane and row:
      in/out blocks + the f32 row accumulators and their stack come to
      ~12 block-planes per row.
    * ``cb`` — largest legal channel tile fitting ``_MR_BWD_VMEM_CAP``
      (via :func:`_pick_cb`); callers must still check
      ``cb * per_cb_bytes <= _MR_BWD_VMEM_CAP`` — when no tile fits,
      _pick_cb falls back to the smallest legal one, which over-allocates
      and crashes Mosaic.
    """
    if hb is None:
        hb = 3 * s
    hb = max(hb - hb % s, s)
    per = w * nb * 12 * hb
    return hb, _pick_cb(c, per, _MR_BWD_VMEM_CAP), per


def max_pool_hwcn_supported(shape, s: int) -> bool:
    """Shapes the hwcn pool kernel compiles for on TPU: the lane dim must
    be full tiles for the bitcast boundary, and the tile the shared plan
    picks for the multi-row backward must actually fit its budget
    (measured: c64/w224 k2s2 fails, c32/w147 and c64/w112 compile)."""
    n, c, h, w = shape
    if n % 128 != 0:
        return False
    _, cb, per = _mp_mr_plan(c, w, 128, s)
    return cb * per <= _MR_BWD_VMEM_CAP


# --------------------------------------------------------------------------
# Max pooling in the native (H, W, C, N) layout.  Same bitcast-boundary
# trick as lrn_pallas_hwcn.  Forward: grid (C, N, OH) with k one-row input
# refs per output row (index maps s*r+i — rows are blocks, so any stride
# is plain indexing); the stride-s window along W uses the pad +
# reshape-split phase form (mosaic_probe).  Backward implements mshadow's
# exact all-ties unpool (``unpool<red::maximum>``: EVERY input equal to
# its window max receives the window's gradient), which XLA's
# select-and-scatter only approximates (one winner) — so this kernel is
# both faster and closer to reference semantics.


def _pool_phases(v, s, wpad, fill):
    """(W, C, N) -> s phase views (wpad/s, C, N) along the major W axis."""
    w, c, n = v.shape
    if w < wpad:
        pad = jnp.full((wpad - w, c, n), fill, v.dtype)
        v = jnp.concatenate([v, pad], axis=0)
    v2 = v.reshape(wpad // s, s, c, n)
    return [v2[:, p] for p in range(s)]


def _mp_hwcn_fwd_kernel(*refs, k, s, ow, wpad, h_in):
    x_rows, o_ref = refs[:k], refs[k]
    r = pl.program_id(2)
    acc = None
    for i in range(k):
        row = x_rows[i][0].astype(jnp.float32)      # (W, C, NB)
        # row i of the window is input row s*r+i; the index map clamps at
        # the edge, so mask clamped reads (clipped tail windows) to -inf
        valid = (s * r + i) < h_in
        row = jnp.where(valid, row, NEG_INF)
        ph = _pool_phases(row, s, wpad, NEG_INF)
        for j in range(k):
            v = ph[j % s][j // s:j // s + ow]
            acc = v if acc is None else jnp.maximum(acc, v)
    o_ref[0] = acc.astype(o_ref.dtype)


def _mp_col_place(ph, pv, dv, k, s, ow, wq, acc):
    """Accumulate one candidate row's column taps into the per-phase
    accumulators (shared by the 1-row and multi-row backward kernels)."""
    for j in range(k):
        q = j // s
        av = ph[j % s][q:q + ow]
        contrib = jnp.where(av == pv, dv, 0.0)
        parts = []
        if q:
            parts.append(jnp.zeros((q,) + contrib.shape[1:], jnp.float32))
        parts.append(contrib)
        if wq - q - ow:
            parts.append(jnp.zeros((wq - q - ow,) + contrib.shape[1:],
                                   jnp.float32))
        placed = parts[0] if len(parts) == 1 \
            else jnp.concatenate(parts, axis=0)
        acc[j % s] = placed if acc[j % s] is None \
            else acc[j % s] + placed
    return acc


def _mp_interleave(acc, a_row, wpad, wq):
    zeros = jnp.zeros((wq,) + a_row.shape[1:], jnp.float32)
    parts = [zeros if v is None else v for v in acc]
    wide = jnp.stack(parts, axis=1).reshape((wpad,) + a_row.shape[1:])
    return wide[:a_row.shape[0]]


def _mp_hwcn_bwd_kernel(*refs, k, s, ow, wpad, oh, h_in, relu_mask=False):
    ncand = -(-k // s)  # output rows touching one input row
    x_ref = refs[0]
    p_refs = refs[1:1 + ncand]
    dp_refs = refs[1 + ncand:1 + 2 * ncand]
    dx_ref = refs[1 + 2 * ncand]
    h = pl.program_id(2)
    a = x_ref[0].astype(jnp.float32)                # (W, C, NB)
    ph = _pool_phases(a, s, wpad, NEG_INF)
    wq = wpad // s
    r0 = (h - (k - 1) + (s - 1)) // s               # first candidate row
    acc = [None] * s
    for cand in range(ncand):
        r = r0 + cand
        pv = p_refs[cand][0].astype(jnp.float32)    # (OW, C, NB)
        dv = dp_refs[cand][0].astype(jnp.float32)
        # tap index i = h - s*r must lie in [0, k) and r in [0, oh)
        i_tap = h - s * jnp.clip(r, 0, oh - 1)
        valid_r = (r >= 0) & (r < oh) & (i_tap >= 0) & (i_tap < k)
        dv = jnp.where(valid_r, dv, 0.0)
        if relu_mask:
            # fused relu backward: pv is the PRE-relu pool output and
            # relu(pv) > 0 iff pv > 0, so masking dv here is exactly
            # where(out > 0, dy, 0) — no separate relu-bwd HBM pass
            dv = jnp.where(pv > 0, dv, 0.0)
        acc = _mp_col_place(ph, pv, dv, k, s, ow, wq, acc)
    dx_ref[0] = _mp_interleave(acc, a, wpad, wq).astype(dx_ref.dtype)


def _mp_hwcn_bwd_kernel_mr(*refs, k, s, ow, wpad, oh, h_in, hb, nref,
                           relu_mask=False):
    """Multi-row backward: hb input rows per program (hb % s == 0, so the
    candidate-row offsets are static per in-block row), p/dp supplied as
    ``nref`` one-row refs starting at the block's first candidate row.
    ``relu_mask`` fuses the deferred-relu backward (pool_relu_fuse): each
    candidate's incoming gradient is zeroed where the pre-relu pool
    output is <= 0, in-register, on the same (hb, cb) tile plan."""
    ncand = -(-k // s)
    x_ref = refs[0]
    p_refs = refs[1:1 + nref]
    dp_refs = refs[1 + nref:1 + 2 * nref]
    dx_ref = refs[1 + 2 * nref]
    bh = pl.program_id(2)
    h0 = bh * hb
    rbase = (h0 - (k - 1) + (s - 1)) // s
    wq = wpad // s
    rel0 = (-(k - 1) + (s - 1)) // s  # rel_j at j=0 (s | h0)
    rows = []
    for j in range(hb):
        a = x_ref[j].astype(jnp.float32)            # (W, C, NB)
        ph = _pool_phases(a, s, wpad, NEG_INF)
        rel_j = (j - (k - 1) + (s - 1)) // s - rel0
        acc = [None] * s
        for cand in range(ncand):
            # absolute candidate row and its static tap index
            i_tap = j - s * ((j - (k - 1) + (s - 1)) // s) - s * cand
            if i_tap < 0 or i_tap >= k:
                continue
            ref_i = rel_j + cand
            r_abs = rbase + ref_i
            pv = p_refs[ref_i][0].astype(jnp.float32)
            dv = dp_refs[ref_i][0].astype(jnp.float32)
            valid = (r_abs >= 0) & (r_abs < oh) & (h0 + j < h_in)
            dv = jnp.where(valid, dv, 0.0)
            if relu_mask:
                # see _mp_hwcn_bwd_kernel: relu'(pool) folded in-register
                dv = jnp.where(pv > 0, dv, 0.0)
            acc = _mp_col_place(ph, pv, dv, k, s, ow, wq, acc)
        rows.append(_mp_interleave(acc, a, wpad, wq))
    dx_ref[...] = jnp.stack(rows, axis=0).astype(dx_ref.dtype)


def _mp_hwcn_fwd(xt, k, s, interpret):
    h, w, c, n = xt.shape
    oh = min(h - k + s - 1, h - 1) // s + 1
    ow = min(w - k + s - 1, w - 1) // s + 1
    # phases must hold the deepest column tap: slice [j//s : j//s + ow]
    # with j up to k-1 needs (k-1)//s + ow entries per phase, which on
    # clipped tail windows (even w, k=3, s=2) exceeds ceil(w/s)
    wpad = max(-(-w // s), (k - 1) // s + ow) * s
    nb = 128 if n % 128 == 0 else n
    cb = _pick_cb(c, (w * nb * 4) * (k + 2), 10 << 20)
    kw = {} if _VMEM is None else {"memory_space": _VMEM}

    x_specs = [
        pl.BlockSpec((1, w, cb, nb),
                     lambda bc, bn, r, i=i: (jnp.minimum(s * r + i, h - 1),
                                             0, bc, bn), **kw)
        for i in range(k)]
    o_spec = pl.BlockSpec((1, ow, cb, nb),
                          lambda bc, bn, r: (r, 0, bc, bn), **kw)
    kern = functools.partial(_mp_hwcn_fwd_kernel, k=k, s=s, ow=ow,
                             wpad=wpad, h_in=h)
    return pl.pallas_call(
        kern,
        grid=(c // cb, n // nb, oh),
        in_specs=x_specs,
        out_specs=o_spec,
        out_shape=jax.ShapeDtypeStruct((oh, ow, c, n), xt.dtype),
        interpret=interpret,
    )(*([xt] * k))


def _mp_hwcn_bwd(xt, pt, dpt, k, s, interpret, hb=None, relu_mask=False):
    h, w, c, n = xt.shape
    oh, ow = pt.shape[0], pt.shape[1]
    wpad = max(-(-w // s), (k - 1) // s + ow) * s  # see _mp_hwcn_fwd
    ncand = -(-k // s)
    nb = 128 if n % 128 == 0 else n
    kw = {} if _VMEM is None else {"memory_space": _VMEM}
    if hb is None or hb > 1:
        # tile plan shared with max_pool_hwcn_supported (_mp_mr_plan).
        # Under _MR_BWD_VMEM_CAP every proven AlexNet shape picks the same
        # tile as the original 14 MB halving loop did
        hb, cb, _ = _mp_mr_plan(c, w, nb, s, hb)
        rel0 = (-(k - 1) + (s - 1)) // s
        rel_last = (hb - 1 - (k - 1) + (s - 1)) // s - rel0
        nref = rel_last + ncand

        def p_imap(i):
            def imap(bc, bn, bh):
                rbase = (bh * hb - (k - 1) + (s - 1)) // s
                return (jnp.clip(rbase + i, 0, oh - 1), 0, bc, bn)
            return imap

        x_spec = pl.BlockSpec((hb, w, cb, nb),
                              lambda bc, bn, bh: (bh, 0, bc, bn), **kw)
        p_specs = [pl.BlockSpec((1, ow, cb, nb), p_imap(i), **kw)
                   for i in range(nref)]
        kern = functools.partial(_mp_hwcn_bwd_kernel_mr, k=k, s=s, ow=ow,
                                 wpad=wpad, oh=oh, h_in=h, hb=hb,
                                 nref=nref, relu_mask=relu_mask)
        return pl.pallas_call(
            kern,
            grid=(c // cb, n // nb, -(-h // hb)),
            in_specs=[x_spec] + p_specs + p_specs,
            out_specs=x_spec,
            out_shape=jax.ShapeDtypeStruct(xt.shape, xt.dtype),
            interpret=interpret,
        )(xt, *([pt] * nref), *([dpt] * nref))

    cb = _pick_cb(c, (w * nb * 4) * (2 * ncand + 4), 10 << 20)

    def cand_imap(cand):
        def imap(bc, bn, hrow):
            r0 = (hrow - (k - 1) + (s - 1)) // s
            return (jnp.clip(r0 + cand, 0, oh - 1), 0, bc, bn)
        return imap

    x_spec = pl.BlockSpec((1, w, cb, nb),
                          lambda bc, bn, hrow: (hrow, 0, bc, bn), **kw)
    p_specs = [pl.BlockSpec((1, ow, cb, nb), cand_imap(i), **kw)
               for i in range(ncand)]
    kern = functools.partial(_mp_hwcn_bwd_kernel, k=k, s=s, ow=ow,
                             wpad=wpad, oh=oh, h_in=h,
                             relu_mask=relu_mask)
    return pl.pallas_call(
        kern,
        grid=(c // cb, n // nb, h),
        in_specs=[x_spec] + p_specs + p_specs,
        out_specs=x_spec,
        out_shape=jax.ShapeDtypeStruct(xt.shape, xt.dtype),
        interpret=interpret,
    )(xt, *([pt] * ncand), *([dpt] * ncand))


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def max_pool_hwcn(x: jnp.ndarray, k: int, s: int) -> jnp.ndarray:
    """Max pool over logical NCHW via (H, W, C, N)-layout Pallas kernels
    (no padding; reference tail-window rule).  Backward = exact mshadow
    all-ties unpool."""
    out, _ = _mp_fwd_res(x, k, s)
    return out


def _mp_fwd_res(x, k, s):
    xt = jnp.transpose(x, (2, 3, 1, 0))
    pt = _mp_hwcn_fwd(xt, k, s, interpret=not _on_tpu())
    return jnp.transpose(pt, (3, 2, 0, 1)), (xt, pt)


def _mp_bwd_res(k, s, res, g):
    xt, pt = res
    dpt = jnp.transpose(g, (2, 3, 1, 0))
    dxt = _mp_hwcn_bwd(xt, pt, dpt, k, s, interpret=not _on_tpu())
    return (jnp.transpose(dxt, (3, 2, 0, 1)),)


max_pool_hwcn.defvjp(_mp_fwd_res, _mp_bwd_res)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def max_pool_relu_hwcn(x: jnp.ndarray, k: int, s: int) -> jnp.ndarray:
    """``relu(max_pool(x))`` with the relu backward FUSED into the
    multi-row all-ties unpool kernel (engine option ``pool_relu_fuse``):
    the deferred-relu mask ``pool_out > 0`` zeroes each candidate's
    incoming gradient in-register on the shared :func:`_mp_mr_plan`
    tile plan, so the stride^2-sized relu-bwd read-modify-write pass
    over the pooled tensor — the SAS+relu cluster's second half —
    disappears.  Residuals are identical to :func:`max_pool_hwcn`
    (``(xt, pt)`` with ``pt`` the PRE-relu pool output; the relu needs
    no extra buffer because ``relu'(pt) = pt > 0``)."""
    out, _ = _mpr_fwd_res(x, k, s)
    return out


def _mpr_fwd_res(x, k, s):
    xt = jnp.transpose(x, (2, 3, 1, 0))
    pt = _mp_hwcn_fwd(xt, k, s, interpret=not _on_tpu())
    y = jnp.maximum(jnp.transpose(pt, (3, 2, 0, 1)), 0)
    return y, (xt, pt)


def _mpr_bwd_res(k, s, res, g):
    xt, pt = res
    dpt = jnp.transpose(g, (2, 3, 1, 0))
    dxt = _mp_hwcn_bwd(xt, pt, dpt, k, s, interpret=not _on_tpu(),
                       relu_mask=True)
    return (jnp.transpose(dxt, (3, 2, 0, 1)),)


max_pool_relu_hwcn.defvjp(_mpr_fwd_res, _mpr_bwd_res)


# --------------------------------------------------------------------------
# Strided-conv weight (+bias) gradient in the native layout.  The round-2
# attempt im2col'd in VMEM per image and died on Mosaic's minor-dim
# reshape limits; this formulation never reshapes: with activations
# transposed to (H, W, C, N) (bitcast, see above), each (row, col)
# position yields a lane-contraction dot
#     acc[o, (tap, ci)] += dy[r, t, o, :] . xs2d[r+dh, t+dw, ci, :]
# — (96, NB) x (448, NB) MXU calls accumulated across the whole grid
# (rows innermost, so the single output block accumulates legally).
# The bias gradient rides along as a lane-preserving row sum.


def _cw_hwcn_kernel(dy_ref, x0_ref, x1_ref, x2_ref, dw_ref, db_ref, acc,
                    accb, *, co, cin_b, kb, ow, taps_pad):
    bn, r = pl.program_id(0), pl.program_id(1)

    @pl.when((bn == 0) & (r == 0))
    def _():
        acc[...] = jnp.zeros_like(acc)

    @pl.when(r == 0)
    def _():
        accb[...] = jnp.zeros_like(accb)

    dy_row = dy_ref[0]                       # (OW, co, NB) bf16
    xs_rows = [x0_ref[0], x1_ref[0], x2_ref[0]][:kb]  # (WB, cin_b, NB)
    a = acc[...]
    for t in range(ow):
        dy_rt = dy_row[t]                    # (co, NB)
        cols = jnp.concatenate(
            [xs_rows[dh][t + dw] for dh in range(kb) for dw in range(kb)]
            + [jnp.zeros((taps_pad - kb * kb * cin_b, dy_rt.shape[1]),
                         xs_rows[0].dtype)] * (taps_pad > kb * kb * cin_b),
            axis=0)                          # (taps_pad, NB)
        a = a + jax.lax.dot_general(
            dy_rt, cols, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
    acc[...] = a
    accb[...] += jnp.sum(dy_row.astype(jnp.float32), axis=0)

    @pl.when((bn == pl.num_programs(0) - 1) & (r == pl.num_programs(1) - 1))
    def _():
        dw_ref[...] = acc[...]

    @pl.when(r == pl.num_programs(1) - 1)
    def _():
        db_ref[0] = accb[...]


def conv_wgrad_hwcn_pallas(x: jnp.ndarray, dy: jnp.ndarray, *, kh: int,
                           kw: int, stride: int, pad_y: int = 0,
                           pad_x: int = 0, nb: int = 128,
                           interpret: bool = None):
    """Weight + bias gradient of a stride-s conv (no groups), logical
    NCHW/OIHW, computed via the s2d identity in (H, W, C, N) layout.

    Returns (dW (co, ci, kh, kw) f32, db (co,) f32).  For the
    small-cin / large-stride geometry class (AlexNet conv1) where XLA's
    dilated-dy wgrad starves the MXU.
    """
    if interpret is None:
        interpret = not _on_tpu()
    from .nn import s2d_input
    n, c, h, w = x.shape
    _, co, oh, ow = dy.shape
    s = stride
    xs2d, kb_y, kb_x = s2d_input(x, s, kh, kw, oh, ow, pad_y, pad_x)
    assert kb_y == kb_x, "square kernels only"
    kb = kb_y
    assert kb <= 3, "kernel blocks up to 3 wired (extend x refs for more)"
    cin_b = c * s * s
    taps = kb * kb * cin_b
    taps_pad = taps  # keep exact; MXU pads internally
    xs_t = jnp.transpose(xs2d, (2, 3, 1, 0))     # (HB, WB, cin_b, N)
    dy_t = jnp.transpose(dy, (2, 3, 1, 0))       # (OH, OW, co, N)
    while n % nb:
        nb //= 2
    kw_ = {} if _VMEM is None else {"memory_space": _VMEM}
    dy_spec = pl.BlockSpec((1, ow, co, nb),
                           lambda bn, r: (r, 0, 0, bn), **kw_)
    # rows r+i for i >= kb are never read; clamp their index maps
    hb = xs_t.shape[0]
    x_specs = [pl.BlockSpec((1, xs_t.shape[1], cin_b, nb),
                            lambda bn, r, i=i: (jnp.minimum(r + i, hb - 1),
                                                0, 0, bn), **kw_)
               for i in range(3)]
    dw_spec = pl.BlockSpec((co, taps_pad), lambda bn, r: (0, 0), **kw_)
    db_spec = pl.BlockSpec((1, co, nb), lambda bn, r: (bn, 0, 0), **kw_)
    kern = functools.partial(_cw_hwcn_kernel, co=co, cin_b=cin_b, kb=kb,
                             ow=ow, taps_pad=taps_pad)
    dw_inner, db_part = pl.pallas_call(
        kern,
        grid=(n // nb, oh),
        in_specs=[dy_spec] + x_specs,
        out_specs=[dw_spec, db_spec],
        out_shape=[jax.ShapeDtypeStruct((co, taps_pad), jnp.float32),
                   jax.ShapeDtypeStruct((n // nb, co, nb), jnp.float32)],
        scratch_shapes=_scratch((co, taps_pad), (co, nb)),
        interpret=interpret,
    )(dy_t, xs_t, xs_t, xs_t)
    db = jnp.sum(db_part, axis=(0, 2))
    # column order is (dh, dw) x (c, sy, sx) — invert to OIHW
    dw6 = dw_inner.reshape(co, kb, kb, c, s, s)
    dw6 = dw6.transpose(0, 3, 1, 4, 2, 5)        # (co, c, kb, sy, kb, sx)
    dwp = dw6.reshape(co, c, kb * s, kb * s)
    return dwp[:, :, :kh, :kw], db


# --------------------------------------------------------------------------
# Flash attention: the sequence stack's hot op.  One VMEM-resident pass per
# (batch*head, q-block), online softmax over k-blocks carried in scratch —
# never materialises the (s, s) score matrix.  Backward recomputes scores
# from the saved logsumexp (two kernels: dq over k-blocks, dk/dv over
# q-blocks).  Same math as parallel/ring.dense_attention's chunked path.
#
# Measured on TPU v5e (b4 h8 s8192 d128 bf16, causal): forward 16.5ms vs
# 53ms for the XLA chunked path (3.2x); fwd+bwd 38.5ms, where the XLA
# path's scan-autodiff residuals (per-chunk f32 scores) exceed HBM
# entirely.  Matmul operands stay bf16 (MXU fast path) with f32
# accumulation; block sizes 512x1024 amortise per-program overhead (the
# first cut at 128x128 ran 131k programs and was slower than XLA).

# --------------------------------------------------------------------------
# Strided-conv weight gradient.  XLA computes the wgrad of a strided conv by
# dilating dy with (stride-1) zeros, so for AlexNet conv1 (11x11 / stride 4 /
# cin 3) ~15/16 of the MXU contraction is zeros (~26% efficiency, BASELINE.md
# profile).  This kernel removes the dilation with the space-to-depth
# identity: the stride-s conv equals a stride-1 conv over s2d-rearranged
# input (ops.nn.conv2d_s2d), whose wgrad is a DENSE contraction
#
#     dW_inner[o, (c*s*s)*(kb*kb)] = sum_{n,oh,ow} dy[n,o,oh,ow] *
#                                    x_s2d[n, c*s*s, oh+dh, ow+dw]
#
# evaluated as one (96 x K) @ (K x 432)-shaped MXU matmul per image, with
# the im2col block built tile-wise in VMEM (never materialised to HBM).
# The (co, ci*s*s, kb, kb) result maps back to OIHW outside the kernel.


def _conv_wgrad_kernel(x_ref, dy_ref, o_ref, ob_ref, acc, accb, *, nb, co,
                       cin_b, oh, ow, kb_y, kb_x):
    @pl.when(pl.program_id(0) == 0)
    def _():
        acc[...] = jnp.zeros_like(acc)
        accb[...] = jnp.zeros_like(accb)

    for i in range(nb):
        dy2 = dy_ref[i].reshape(co, oh * ow)
        cols = jnp.concatenate(
            [x_ref[i, :, dh:dh + oh, dw:dw + ow].reshape(cin_b, oh * ow)
             for dh in range(kb_y) for dw in range(kb_x)], axis=0)
        acc[...] += jax.lax.dot_general(
            dy2, cols, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        # bias grad rides along: dy is already in VMEM, so the row-sum is
        # free compared to the separate full-activation reduce XLA emits
        accb[...] += jnp.sum(dy2.astype(jnp.float32), axis=1)[None, :]

    @pl.when(pl.program_id(0) == pl.num_programs(0) - 1)
    def _():
        o_ref[...] = acc[...]
        ob_ref[...] = accb[...]


def conv_wgrad_s2d_pallas(x: jnp.ndarray, dy: jnp.ndarray, *, kh: int,
                          kw: int, stride: int, pad_y: int = 0,
                          pad_x: int = 0, nb: int = 8,
                          interpret: bool = None):
    """Weight + bias gradient of a stride-s 2D conv (no groups), NCHW/OIHW.

    Returns ``(dW (co, ci, kh, kw), db (co,))`` in float32.  Intended for
    the small-input-channel / large-stride geometry class (AlexNet conv1)
    where XLA's dilated-dy formulation starves the MXU; see module comment.
    """
    if interpret is None:
        interpret = not _on_tpu()
    from .nn import s2d_input
    n, c, h, w = x.shape
    _, co, oh, ow = dy.shape
    s = stride
    xs2d, kb_y, kb_x = s2d_input(x, s, kh, kw, oh, ow, pad_y, pad_x)
    cin_b = c * s * s
    while n % nb != 0:
        nb //= 2
    kern = functools.partial(_conv_wgrad_kernel, nb=nb, co=co, cin_b=cin_b,
                             oh=oh, ow=ow, kb_y=kb_y, kb_x=kb_x)
    ncols = cin_b * kb_y * kb_x
    hb, wb = oh - 1 + kb_y, ow - 1 + kb_x
    dw_inner, db = pl.pallas_call(
        kern,
        grid=(n // nb,),
        in_specs=[pl.BlockSpec((nb, cin_b, hb, wb), lambda i: (i, 0, 0, 0)),
                  pl.BlockSpec((nb, co, oh, ow), lambda i: (i, 0, 0, 0))],
        out_specs=[pl.BlockSpec((co, ncols), lambda i: (0, 0)),
                   pl.BlockSpec((1, co), lambda i: (0, 0))],
        out_shape=[jax.ShapeDtypeStruct((co, ncols), jnp.float32),
                   jax.ShapeDtypeStruct((1, co), jnp.float32)],
        scratch_shapes=_scratch((co, ncols), (1, co)),
        interpret=interpret,
    )(xs2d, dy)
    # invert conv2d_s2d's weight layout: columns are ordered
    # (seg=(dh,dw)) x (c, sy, sx); padded taps (dh*s+sy >= kh) are zero in
    # the contraction and sliced away here
    dw6 = dw_inner.reshape(co, kb_y, kb_x, c, s, s)
    dw6 = dw6.transpose(0, 3, 1, 4, 2, 5)  # (co, c, kb_y, sy, kb_x, sx)
    dwp = dw6.reshape(co, c, kb_y * s, kb_x * s)
    return dwp[:, :, :kh, :kw], db[0]


NEG_INF = -1e30

# NOTE: grid dimension_semantics annotations were swept on v5e
# (experiments/fa_tune.py) and measured exactly neutral, so the kernels
# ship unannotated.  Do not add PARALLEL to the q-block grid dim of the
# forward kernel without restructuring lse: its (1, 1, s) output block is
# shared across q-block programs, which a megacore split would corrupt.


def _fa_blocks(s_len, d=64):
    """Block sizes: big blocks amortize per-program overhead and k/v
    re-fetches; must divide the sequence length and satisfy the (8, 128)
    tile minimum.  (1024, 1024) won the v5e sweep at s4096 for both head
    widths (experiments/fa_tune.py: fwd 6.84 vs 7.66 ms at dh64, 3.26 vs
    3.66 at dh128, bwd equal-or-better); scores stay ~8 MB f32 in VMEM.
    Wider heads (d > 128, unswept) keep the old (512, 1024) shape so the
    bwd kernels' block-sized f32 intermediates stay inside VMEM."""
    bq, bk = (1024, 1024) if d <= 128 else (512, 1024)
    while bq > 128 and s_len % bq != 0:
        bq //= 2
    while bk > 128 and s_len % bk != 0:
        bk //= 2
    return bq, bk


def _causal_mask(s, i, j, bq, bk):
    qpos = i * bq + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    kpos = j * bk + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(qpos >= kpos, s, NEG_INF)


def _segment_mask(s, i, j, bq, bk, segq, segk):
    """Document-packing segment mask on an already-causal-masked score
    block: keep (same segment & segment != 0) | diagonal.  The diagonal
    stays unconditionally allowed so padding rows (segment 0) attend
    themselves and the online softmax never renormalizes a fully-masked
    row — the SAME rule as the lax fallback (parallel/ring.py module
    docstring), which the pairtests hold this kernel to."""
    qpos = i * bq + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    kpos = j * bk + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    same = (segq[:, None] == segk[None, :]) & (segq[:, None] != 0)
    return jnp.where(same | (qpos == kpos), s, NEG_INF)


def _fa_fwd_init(acc, m, l):
    acc[...] = jnp.zeros_like(acc)
    m[...] = jnp.full_like(m, NEG_INF)
    l[...] = jnp.zeros_like(l)


def _fa_fwd_step(i, j, q_ref, k_ref, v_ref, acc, m, l, *, scale, causal,
                 bq, bk, segq=None, segk=None):
    """One online-softmax block update — the SINGLE copy of the forward
    math, shared by the dense, triangular-grid, and segmented kernels."""
    # keep matmul operands in the input dtype (bf16 hits the MXU's fast
    # path); accumulate in f32 via preferred_element_type
    qb, kb, vb = q_ref[0], k_ref[0], v_ref[0]
    s = jax.lax.dot_general(qb, kb, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    if causal:
        s = _causal_mask(s, i, j, bq, bk)
    if segq is not None:
        s = _segment_mask(s, i, j, bq, bk, segq, segk)
    m_prev = m[...]
    m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)
    corr = jnp.exp(m_prev - m_new)
    l[...] = l[...] * corr + p.sum(axis=-1, keepdims=True)
    acc[...] = acc[...] * corr + jax.lax.dot_general(
        p.astype(vb.dtype), vb, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    m[...] = m_new


def _fa_fwd_emit(i, o_ref, lse_ref, acc, m, l, bq):
    o_ref[0] = (acc[...] / l[...]).astype(o_ref.dtype)
    lse_ref[0, 0, pl.ds(i * bq, bq)] = (m[...] + jnp.log(l[...]))[:, 0]


def _fa_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m, l,
                   *, scale, causal, bq, bk):
    i, j = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _():
        _fa_fwd_init(acc, m, l)

    # causal: blocks strictly above the diagonal contribute nothing
    live = (i * bq + bq - 1 >= j * bk) if causal else (j >= 0)

    @pl.when(live)
    def _():
        _fa_fwd_step(i, j, q_ref, k_ref, v_ref, acc, m, l, scale=scale,
                     causal=causal, bq=bq, bk=bk)

    @pl.when(j == nk - 1)
    def _():
        _fa_fwd_emit(i, o_ref, lse_ref, acc, m, l, bq)


def _fa_p_ds(i, j, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *,
             scale, causal, bq, bk, segq=None, segk=None):
    """Recompute p and ds for one block pair — the SINGLE copy of the
    backward score math, shared by dq/dkv in both grid forms."""
    qb, kb = q_ref[0], k_ref[0]
    s = jax.lax.dot_general(qb, kb, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    if causal:
        s = _causal_mask(s, i, j, bq, bk)
    if segq is not None:
        s = _segment_mask(s, i, j, bq, bk, segq, segk)
    p = jnp.exp(s - lse_ref[0, 0, pl.ds(i * bq, bq)][:, None])
    dob = do_ref[0]
    dp = jax.lax.dot_general(dob, v_ref[0], (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - delta_ref[0, 0, pl.ds(i * bq, bq)][:, None]) * scale
    return p, ds, dob, qb, kb


def _fa_dq_step(i, j, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dq_acc, *, scale, causal, bq, bk, segq=None, segk=None):
    _, ds, _, _, kb = _fa_p_ds(i, j, q_ref, k_ref, v_ref, do_ref,
                               lse_ref, delta_ref, scale=scale,
                               causal=causal, bq=bq, bk=bk,
                               segq=segq, segk=segk)
    dq_acc[...] += jax.lax.dot_general(
        ds.astype(kb.dtype), kb, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def _fa_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                  dq_acc, *, scale, causal, bq, bk):
    i, j = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    live = (i * bq + bq - 1 >= j * bk) if causal else (j >= 0)

    @pl.when(live)
    def _():
        _fa_dq_step(i, j, q_ref, k_ref, v_ref, do_ref, lse_ref,
                    delta_ref, dq_acc, scale=scale, causal=causal,
                    bq=bq, bk=bk)

    @pl.when(j == nk - 1)
    def _():
        dq_ref[0] = dq_acc[...].astype(dq_ref.dtype)


def _fa_dkv_step(i, j, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                 dk_acc, dv_acc, *, scale, causal, bq, bk,
                 segq=None, segk=None):
    p, ds, dob, qb, _ = _fa_p_ds(i, j, q_ref, k_ref, v_ref, do_ref,
                                 lse_ref, delta_ref, scale=scale,
                                 causal=causal, bq=bq, bk=bk,
                                 segq=segq, segk=segk)
    dv_acc[...] += jax.lax.dot_general(
        p.astype(dob.dtype), dob, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    dk_acc[...] += jax.lax.dot_general(
        ds.astype(qb.dtype), qb, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def _fa_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dk_ref, dv_ref, dk_acc, dv_acc, *, scale, causal, bq, bk):
    j, i = pl.program_id(1), pl.program_id(2)  # note: k-block is grid dim 1
    nq = pl.num_programs(2)

    @pl.when(i == 0)
    def _():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    live = (i * bq + bq - 1 >= j * bk) if causal else (i >= 0)

    @pl.when(live)
    def _():
        _fa_dkv_step(i, j, q_ref, k_ref, v_ref, do_ref, lse_ref,
                     delta_ref, dk_acc, dv_acc, scale=scale,
                     causal=causal, bq=bq, bk=bk)

    @pl.when(i == nq - 1)
    def _():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _scratch(*shapes):
    assert pltpu is not None, "flash attention needs pallas TPU support"
    return [pltpu.VMEM(s, jnp.float32) for s in shapes]


def flash_attention_available(s_len: int, d: int) -> bool:
    return pltpu is not None and s_len % 128 == 0 and d <= 256


def _fa_tri_pairs(nq, nk, bq, bk, order):
    """Live (i, j) block pairs of the causal triangle, as int32 arrays.
    order="ij": i-major (dq/fwd: j accumulates within a row);
    order="ji": j-major (dkv: i accumulates within a column).  Dead
    blocks (i*bq+bq-1 < j*bk) are EXCLUDED from the grid entirely, so
    neither their DMA nor their program overhead is paid — with equal
    1024-blocks at s4096 that is 6 of 16 programs."""
    import numpy as _np
    pairs = [(i, j) for i in range(nq) for j in range(nk)
             if i * bq + bq - 1 >= j * bk]
    if order == "ji":
        pairs.sort(key=lambda ij: (ij[1], ij[0]))
    ii = _np.asarray([p[0] for p in pairs], _np.int32)
    jj = _np.asarray([p[1] for p in pairs], _np.int32)
    return jnp.asarray(ii), jnp.asarray(jj)


def _fa_fwd_kernel_tri(ii_ref, jj_ref, q_ref, k_ref, v_ref, o_ref,
                       lse_ref, acc, m, l, *, scale, bq, bk):
    t = pl.program_id(1)
    i, j = ii_ref[t], jj_ref[t]
    jlast = (i * bq + bq - 1) // bk

    @pl.when(j == 0)
    def _():
        _fa_fwd_init(acc, m, l)

    _fa_fwd_step(i, j, q_ref, k_ref, v_ref, acc, m, l, scale=scale,
                 causal=True, bq=bq, bk=bk)

    @pl.when(j == jlast)
    def _():
        _fa_fwd_emit(i, o_ref, lse_ref, acc, m, l, bq)


def _fa_dq_kernel_tri(ii_ref, jj_ref, q_ref, k_ref, v_ref, do_ref,
                      lse_ref, delta_ref, dq_ref, dq_acc, *, scale, bq, bk):
    t = pl.program_id(1)
    i, j = ii_ref[t], jj_ref[t]
    jlast = (i * bq + bq - 1) // bk

    @pl.when(j == 0)
    def _():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    _fa_dq_step(i, j, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dq_acc, scale=scale, causal=True, bq=bq, bk=bk)

    @pl.when(j == jlast)
    def _():
        dq_ref[0] = dq_acc[...].astype(dq_ref.dtype)


def _fa_dkv_kernel_tri(ii_ref, jj_ref, q_ref, k_ref, v_ref, do_ref,
                       lse_ref, delta_ref, dk_ref, dv_ref, dk_acc, dv_acc,
                       *, scale, bq, bk, nq):
    t = pl.program_id(1)
    i, j = ii_ref[t], jj_ref[t]
    ifirst = (j * bk) // bq

    @pl.when(i == ifirst)
    def _():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    _fa_dkv_step(i, j, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                 dk_acc, dv_acc, scale=scale, causal=True, bq=bq, bk=bk)

    @pl.when(i == nq - 1)
    def _():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _fa_tri_specs(s_len, d, bq, bk):
    """Block specs for the (nbh, T) triangular grid: index maps read the
    live pair arrays from scalar prefetch (convention: index_map(*grid,
    *scalar_refs))."""
    q_spec = pl.BlockSpec((1, bq, d), lambda b, t, ii, jj: (b, ii[t], 0))
    k_spec = pl.BlockSpec((1, bk, d), lambda b, t, ii, jj: (b, jj[t], 0))
    row_spec = pl.BlockSpec((1, 1, s_len), lambda b, t, ii, jj: (b, 0, 0))
    return q_spec, k_spec, row_spec


def _fa_specs(nbh, s_len, d, bq, bk):
    # row vectors (lse, delta) ride as whole (1, s) blocks pinned per batch
    # row: a (1, bq) block would violate the (8, 128) tile minimum
    q_spec = pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0))
    k_spec = pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0))
    row_spec = pl.BlockSpec((1, 1, s_len), lambda b, i, j: (b, 0, 0))
    return q_spec, k_spec, row_spec


def _fa_fwd(q3, k3, v3, scale, causal, interpret):
    nbh, s_len, d = q3.shape
    bq, bk = _fa_blocks(s_len, d)
    if causal:
        # triangular grid: dead above-diagonal blocks are excluded from
        # the grid, so neither their k/v DMA nor program overhead is paid
        # (with equal 1024-blocks at s4096: 6 of 16 programs).  Also runs
        # under interpret so the CPU parity tests cover this path.
        ii, jj = _fa_tri_pairs(s_len // bq, s_len // bk, bq, bk, "ij")
        q_spec, k_spec, row_spec = _fa_tri_specs(s_len, d, bq, bk)
        gs = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2, grid=(nbh, ii.shape[0]),
            in_specs=[q_spec, k_spec, k_spec],
            out_specs=[q_spec, row_spec],
            scratch_shapes=_scratch((bq, d), (bq, 1), (bq, 1)))
        kern = functools.partial(_fa_fwd_kernel_tri, scale=scale,
                                 bq=bq, bk=bk)
        return pl.pallas_call(
            kern, grid_spec=gs, interpret=interpret,
            out_shape=[jax.ShapeDtypeStruct(q3.shape, q3.dtype),
                       jax.ShapeDtypeStruct((nbh, 1, s_len), jnp.float32)],
        )(ii, jj, q3, k3, v3)
    q_spec, k_spec, row_spec = _fa_specs(nbh, s_len, d, bq, bk)
    kern = functools.partial(_fa_fwd_kernel, scale=scale, causal=causal,
                             bq=bq, bk=bk)
    o, lse = pl.pallas_call(
        kern,
        grid=(nbh, s_len // bq, s_len // bk),
        in_specs=[q_spec, k_spec, k_spec],
        out_specs=[q_spec, row_spec],
        out_shape=[jax.ShapeDtypeStruct(q3.shape, q3.dtype),
                   jax.ShapeDtypeStruct((nbh, 1, s_len), jnp.float32)],
        scratch_shapes=_scratch((bq, d), (bq, 1), (bq, 1)),
        interpret=interpret,
    )(q3, k3, v3)
    return o, lse


def _fa_bwd(q3, k3, v3, o3, lse, g3, scale, causal, interpret):
    nbh, s_len, d = q3.shape
    delta = jnp.sum(g3.astype(jnp.float32) * o3.astype(jnp.float32),
                    axis=-1)[:, None, :]  # (nbh, 1, s)
    bq, bk = _fa_blocks(s_len, d)
    if causal:
        nq, nk = s_len // bq, s_len // bk
        q_spec, k_spec, row_spec = _fa_tri_specs(s_len, d, bq, bk)
        ii, jj = _fa_tri_pairs(nq, nk, bq, bk, "ij")
        gs = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2, grid=(nbh, ii.shape[0]),
            in_specs=[q_spec, k_spec, k_spec, q_spec, row_spec, row_spec],
            out_specs=q_spec,
            scratch_shapes=_scratch((bq, d)))
        dq = pl.pallas_call(
            functools.partial(_fa_dq_kernel_tri, scale=scale, bq=bq,
                              bk=bk),
            grid_spec=gs, interpret=interpret,
            out_shape=jax.ShapeDtypeStruct(q3.shape, q3.dtype),
        )(ii, jj, q3, k3, v3, g3, lse, delta)
        ii2, jj2 = _fa_tri_pairs(nq, nk, bq, bk, "ji")
        gs2 = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2, grid=(nbh, ii2.shape[0]),
            in_specs=[q_spec, k_spec, k_spec, q_spec, row_spec, row_spec],
            out_specs=[k_spec, k_spec],
            scratch_shapes=_scratch((bk, d), (bk, d)))
        dk, dv = pl.pallas_call(
            functools.partial(_fa_dkv_kernel_tri, scale=scale, bq=bq,
                              bk=bk, nq=nq),
            grid_spec=gs2, interpret=interpret,
            out_shape=[jax.ShapeDtypeStruct(k3.shape, k3.dtype),
                       jax.ShapeDtypeStruct(v3.shape, v3.dtype)],
        )(ii2, jj2, q3, k3, v3, g3, lse, delta)
        return dq, dk, dv
    q_spec, k_spec, row_spec = _fa_specs(nbh, s_len, d, bq, bk)
    dq = pl.pallas_call(
        functools.partial(_fa_dq_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk),
        grid=(nbh, s_len // bq, s_len // bk),
        in_specs=[q_spec, k_spec, k_spec, q_spec, row_spec, row_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct(q3.shape, q3.dtype),
        scratch_shapes=_scratch((bq, d)),
        interpret=interpret,
    )(q3, k3, v3, g3, lse, delta)
    # k-block outer, q-block inner: accumulate dk/dv per k-block
    kq_q_spec = pl.BlockSpec((1, bq, d), lambda b, j, i: (b, i, 0))
    kq_k_spec = pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0))
    kq_row_spec = pl.BlockSpec((1, 1, s_len), lambda b, j, i: (b, 0, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_fa_dkv_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk),
        grid=(nbh, s_len // bk, s_len // bq),
        in_specs=[kq_q_spec, kq_k_spec, kq_k_spec, kq_q_spec,
                  kq_row_spec, kq_row_spec],
        out_specs=[kq_k_spec, kq_k_spec],
        out_shape=[jax.ShapeDtypeStruct(k3.shape, k3.dtype),
                   jax.ShapeDtypeStruct(v3.shape, v3.dtype)],
        scratch_shapes=_scratch((bk, d), (bk, d)),
        interpret=interpret,
    )(q3, k3, v3, g3, lse, delta)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention(q, k, v, causal: bool = False,
                    scale: float = None, interpret: bool = None):
    """Flash attention, (b, h, s, d) -> (b, h, s, d).

    Requires s divisible by 128 (use ``flash_attention_available``);
    ``interpret`` defaults to off-TPU detection so tests run on CPU.
    """
    out, _ = _flash_fwd_res(q, k, v, causal, scale, interpret)
    return out


def _norm_args(q, causal, scale, interpret):
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    if interpret is None:
        interpret = not _on_tpu()
    return scale, interpret


def _flash_fwd_res(q, k, v, causal, scale, interpret):
    scale, interpret = _norm_args(q, causal, scale, interpret)
    b, h, s_len, d = q.shape
    sh3 = (b * h, s_len, d)
    o3, lse = _fa_fwd(q.reshape(sh3), k.reshape(sh3), v.reshape(sh3),
                      scale, causal, interpret)
    return o3.reshape(q.shape), (q, k, v, o3, lse)


def _flash_bwd_res(causal, scale, interpret, res, g):
    q, k, v, o3, lse = res
    scale, interpret = _norm_args(q, causal, scale, interpret)
    b, h, s_len, d = q.shape
    sh3 = (b * h, s_len, d)
    dq, dk, dv = _fa_bwd(q.reshape(sh3), k.reshape(sh3), v.reshape(sh3),
                         o3, lse, g.reshape(sh3), scale, causal, interpret)
    return (dq.reshape(q.shape), dk.reshape(k.shape), dv.reshape(v.shape))


flash_attention.defvjp(_flash_fwd_res, _flash_bwd_res)


# --------------------------------------------------------------------------
# Segment-masked causal flash attention (document packing, io/text.py).
# Same triangular live-pair grid as the causal kernels — segment masking
# only REMOVES scores inside live blocks, so the grid, block specs, and
# online-softmax state are unchanged; the per-position segment-id row
# rides as one (1, 1, s) int32 block exactly like lse/delta.  The mask
# rule is shared verbatim with the lax fallback (_segment_mask /
# parallel/ring.py), and the interpret-mode pairtests hold the two paths
# together (tests/test_text.py).


def _fa_seg_slices(seg_ref, i, j, bq, bk):
    return (seg_ref[0, 0, pl.ds(i * bq, bq)],
            seg_ref[0, 0, pl.ds(j * bk, bk)])


def _fa_fwd_kernel_tri_seg(ii_ref, jj_ref, q_ref, k_ref, v_ref, seg_ref,
                           o_ref, lse_ref, acc, m, l, *, scale, bq, bk):
    t = pl.program_id(1)
    i, j = ii_ref[t], jj_ref[t]
    jlast = (i * bq + bq - 1) // bk

    @pl.when(j == 0)
    def _():
        _fa_fwd_init(acc, m, l)

    segq, segk = _fa_seg_slices(seg_ref, i, j, bq, bk)
    _fa_fwd_step(i, j, q_ref, k_ref, v_ref, acc, m, l, scale=scale,
                 causal=True, bq=bq, bk=bk, segq=segq, segk=segk)

    @pl.when(j == jlast)
    def _():
        _fa_fwd_emit(i, o_ref, lse_ref, acc, m, l, bq)


def _fa_dq_kernel_tri_seg(ii_ref, jj_ref, q_ref, k_ref, v_ref, do_ref,
                          lse_ref, delta_ref, seg_ref, dq_ref, dq_acc,
                          *, scale, bq, bk):
    t = pl.program_id(1)
    i, j = ii_ref[t], jj_ref[t]
    jlast = (i * bq + bq - 1) // bk

    @pl.when(j == 0)
    def _():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    segq, segk = _fa_seg_slices(seg_ref, i, j, bq, bk)
    _fa_dq_step(i, j, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dq_acc, scale=scale, causal=True, bq=bq, bk=bk,
                segq=segq, segk=segk)

    @pl.when(j == jlast)
    def _():
        dq_ref[0] = dq_acc[...].astype(dq_ref.dtype)


def _fa_dkv_kernel_tri_seg(ii_ref, jj_ref, q_ref, k_ref, v_ref, do_ref,
                           lse_ref, delta_ref, seg_ref, dk_ref, dv_ref,
                           dk_acc, dv_acc, *, scale, bq, bk, nq):
    t = pl.program_id(1)
    i, j = ii_ref[t], jj_ref[t]
    ifirst = (j * bk) // bq

    @pl.when(i == ifirst)
    def _():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    segq, segk = _fa_seg_slices(seg_ref, i, j, bq, bk)
    _fa_dkv_step(i, j, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                 dk_acc, dv_acc, scale=scale, causal=True, bq=bq, bk=bk,
                 segq=segq, segk=segk)

    @pl.when(i == nq - 1)
    def _():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _fa_seg_fwd(q3, k3, v3, seg3, scale, interpret):
    nbh, s_len, d = q3.shape
    bq, bk = _fa_blocks(s_len, d)
    ii, jj = _fa_tri_pairs(s_len // bq, s_len // bk, bq, bk, "ij")
    q_spec, k_spec, row_spec = _fa_tri_specs(s_len, d, bq, bk)
    gs = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2, grid=(nbh, ii.shape[0]),
        in_specs=[q_spec, k_spec, k_spec, row_spec],
        out_specs=[q_spec, row_spec],
        scratch_shapes=_scratch((bq, d), (bq, 1), (bq, 1)))
    kern = functools.partial(_fa_fwd_kernel_tri_seg, scale=scale,
                             bq=bq, bk=bk)
    return pl.pallas_call(
        kern, grid_spec=gs, interpret=interpret,
        out_shape=[jax.ShapeDtypeStruct(q3.shape, q3.dtype),
                   jax.ShapeDtypeStruct((nbh, 1, s_len), jnp.float32)],
    )(ii, jj, q3, k3, v3, seg3)


def _fa_seg_bwd(q3, k3, v3, seg3, o3, lse, g3, scale, interpret):
    nbh, s_len, d = q3.shape
    delta = jnp.sum(g3.astype(jnp.float32) * o3.astype(jnp.float32),
                    axis=-1)[:, None, :]
    bq, bk = _fa_blocks(s_len, d)
    nq, nk = s_len // bq, s_len // bk
    q_spec, k_spec, row_spec = _fa_tri_specs(s_len, d, bq, bk)
    ii, jj = _fa_tri_pairs(nq, nk, bq, bk, "ij")
    gs = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2, grid=(nbh, ii.shape[0]),
        in_specs=[q_spec, k_spec, k_spec, q_spec, row_spec, row_spec,
                  row_spec],
        out_specs=q_spec,
        scratch_shapes=_scratch((bq, d)))
    dq = pl.pallas_call(
        functools.partial(_fa_dq_kernel_tri_seg, scale=scale, bq=bq, bk=bk),
        grid_spec=gs, interpret=interpret,
        out_shape=jax.ShapeDtypeStruct(q3.shape, q3.dtype),
    )(ii, jj, q3, k3, v3, g3, lse, delta, seg3)
    ii2, jj2 = _fa_tri_pairs(nq, nk, bq, bk, "ji")
    gs2 = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2, grid=(nbh, ii2.shape[0]),
        in_specs=[q_spec, k_spec, k_spec, q_spec, row_spec, row_spec,
                  row_spec],
        out_specs=[k_spec, k_spec],
        scratch_shapes=_scratch((bk, d), (bk, d)))
    dk, dv = pl.pallas_call(
        functools.partial(_fa_dkv_kernel_tri_seg, scale=scale, bq=bq,
                          bk=bk, nq=nq),
        grid_spec=gs2, interpret=interpret,
        out_shape=[jax.ShapeDtypeStruct(k3.shape, k3.dtype),
                   jax.ShapeDtypeStruct(v3.shape, v3.dtype)],
    )(ii2, jj2, q3, k3, v3, g3, lse, delta, seg3)
    return dq, dk, dv


def _seg_tile(seg, h):
    """(b, s) segment ids -> the kernels' (b*h, 1, s) int32 layout
    (b-major, matching ``q.reshape(b*h, s, d)``)."""
    return jnp.repeat(seg.astype(jnp.int32)[:, None, :], h, axis=0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _flash_seg(q, k, v, seg, scale, interpret):
    out, _ = _flash_seg_fwd_res(q, k, v, seg, scale, interpret)
    return out


def _flash_seg_fwd_res(q, k, v, seg, scale, interpret):
    scale, interpret = _norm_args(q, True, scale, interpret)
    b, h, s_len, d = q.shape
    sh3 = (b * h, s_len, d)
    seg3 = _seg_tile(seg, h)
    o3, lse = _fa_seg_fwd(q.reshape(sh3), k.reshape(sh3), v.reshape(sh3),
                          seg3, scale, interpret)
    return o3.reshape(q.shape), (q, k, v, seg, o3, lse)


def _flash_seg_bwd_res(scale, interpret, res, g):
    q, k, v, seg, o3, lse = res
    scale, interpret = _norm_args(q, True, scale, interpret)
    b, h, s_len, d = q.shape
    sh3 = (b * h, s_len, d)
    dq, dk, dv = _fa_seg_bwd(q.reshape(sh3), k.reshape(sh3),
                             v.reshape(sh3), _seg_tile(seg, h), o3, lse,
                             g.reshape(sh3), scale, interpret)
    import numpy as _np
    dseg = _np.zeros(seg.shape, jax.dtypes.float0)  # int input: no tangent
    return (dq.reshape(q.shape), dk.reshape(k.shape),
            dv.reshape(v.shape), dseg)


_flash_seg.defvjp(_flash_seg_fwd_res, _flash_seg_bwd_res)


def flash_attention_segmented(q, k, v, seg, scale=None, interpret=None):
    """Segment-masked causal flash attention, (b, h, s, d) + (b, s) int
    segment ids -> (b, h, s, d).

    Block-diagonal causal masking for packed documents (segment 0 =
    padding; the diagonal is always allowed — see ``_segment_mask``).
    Same availability gate as :func:`flash_attention`
    (``flash_attention_available``); ``interpret`` defaults to off-TPU
    detection so the CPU pairtests run this exact code."""
    return _flash_seg(q, k, v, seg, scale, interpret)


# --------------------------------------------------------------------------
# LayerNorm over the minor axis, (rows, d) in VMEM row-blocks.  The XLA
# lowering of the d2048 transformer left ~1.9 ms/site convert_reduce
# fusions in the step (25 sites, 47.9 ms/step) for an op whose standalone
# cost is 0.094 ms — the fusion stalls on an operand copy the scheduler
# chains it behind.  A custom-vjp kernel pins both passes to single
# VMEM-resident sweeps.
#
# Residual contract (round 6, "stats-only"): the round-5 kernel saved the
# INPUT x as a residual, pinning a (rows, d) buffer per site (~64 MB x 25
# sites at the d2048 flagship) that XLA's auto-remat had been recomputing
# from the cheap residual-stream adds — enabling pallas_ln then OOM'd the
# flagship by 0.8 GB.  The backward is now formulated from the OUTPUT:
#
#     xhat = (y - beta) / gamma
#     dx   = rstd * (dy*gamma - mean_d(dy*gamma) - xhat * mean_d(dy*gamma*xhat))
#     dgamma = sum_rows(dy * xhat);  dbeta = sum_rows(dy)
#
# so the residuals are (y, gamma, beta, rstd): y is the op's own primal
# output (the SAME value, not a copy — under jit the residual aliases the
# output buffer, which the downstream matmul wgrad keeps live anyway), and
# everything else is O(rows) f32 stats or (d,) vectors.  No (rows, d)
# buffer beyond the output exists in the vjp pytree, and the input x is
# free to be rematerialized — this is the FlashAttention idiom (keep
# O(rows) softmax/normalization stats, rebuild the O(rows*d) intermediate
# inside the backward kernel) applied to LN.
#
# Caveats of the rebuild (see doc/pallas_ln.md):
# * columns where gamma is EXACTLY zero lose xhat — the kernel
#   substitutes xhat=0 there (a stop-gradient of the normalized value,
#   not an inf).  gamma init is 1.0; training leaves exact zeros
#   measure-zero.
# * precision: xhat carries the STORED-dtype rounding of y amplified by
#   the y-beta cancellation — abs error ~ eps_dtype*(|y|+|beta|)/|gamma|.
#   For beta ~ 0 this reduces to eps_dtype*|xhat| (benign, gamma
#   cancels); it bites in bf16 when |beta| >> |gamma|.  ``save_x=True``
#   (config ``pallas_ln = x``) restores the round-5 input-saving
#   residuals for precision-critical configs, re-accepting the HBM pin.
# dgamma/dbeta accumulate across row-blocks in scratch (grid dim 0 is
# sequential, so the accumulation is legal, as in conv_wgrad's pattern).


def _ln_fwd_kernel(x_ref, g_ref, b_ref, y_ref, m_ref, r_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)
    mean = x.mean(axis=1, keepdims=True)
    # two-pass variance: x is VMEM-resident so the second sweep is free,
    # and E[x^2]-E[x]^2 cancels catastrophically for high-mean rows
    var = jnp.square(x - mean).mean(axis=1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    y = (x - mean) * rstd * g_ref[...].astype(jnp.float32) \
        + b_ref[...].astype(jnp.float32)
    y_ref[...] = y.astype(y_ref.dtype)
    m_ref[...] = mean
    r_ref[...] = rstd


def _ln_bwd_kernel(y_ref, g_ref, b_ref, r_ref, dy_ref, dx_ref, dg_ref,
                   db_ref, dg_acc, db_acc):
    i = pl.program_id(0)
    y = y_ref[...].astype(jnp.float32)
    dy = dy_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32)
    b = b_ref[...].astype(jnp.float32)
    rstd = r_ref[...]
    # rebuild xhat from the output (see residual contract above); columns
    # with gamma exactly 0 carry no xhat information — substitute 0
    zero_g = g == 0.0
    xhat = jnp.where(zero_g, 0.0, (y - b) / jnp.where(zero_g, 1.0, g))
    dyg = dy * g
    c1 = dyg.mean(axis=1, keepdims=True)
    c2 = (dyg * xhat).mean(axis=1, keepdims=True)
    dx_ref[...] = (rstd * (dyg - c1 - xhat * c2)).astype(dx_ref.dtype)

    @pl.when(i == 0)
    def _():
        dg_acc[...] = jnp.zeros_like(dg_acc)
        db_acc[...] = jnp.zeros_like(db_acc)
    dg_acc[...] += jnp.sum(dy * xhat, axis=0, keepdims=True)
    db_acc[...] += jnp.sum(dy, axis=0, keepdims=True)

    @pl.when(i == pl.num_programs(0) - 1)
    def _():
        dg_ref[...] = dg_acc[...]
        db_ref[...] = db_acc[...]


def _ln_bwd_kernel_x(x_ref, g_ref, m_ref, r_ref, dy_ref, dx_ref, dg_ref,
                     db_ref, dg_acc, db_acc):
    """save_x backward (the round-5 form): xhat from the saved INPUT and
    stats — no gamma division, so no cancellation amplification; costs
    the pinned (rows, d) input residual."""
    i = pl.program_id(0)
    x = x_ref[...].astype(jnp.float32)
    dy = dy_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32)
    mean, rstd = m_ref[...], r_ref[...]
    xhat = (x - mean) * rstd
    dyg = dy * g
    c1 = dyg.mean(axis=1, keepdims=True)
    c2 = (dyg * xhat).mean(axis=1, keepdims=True)
    dx_ref[...] = (rstd * (dyg - c1 - xhat * c2)).astype(dx_ref.dtype)

    @pl.when(i == 0)
    def _():
        dg_acc[...] = jnp.zeros_like(dg_acc)
        db_acc[...] = jnp.zeros_like(db_acc)
    dg_acc[...] += jnp.sum(dy * xhat, axis=0, keepdims=True)
    db_acc[...] += jnp.sum(dy, axis=0, keepdims=True)

    @pl.when(i == pl.num_programs(0) - 1)
    def _():
        dg_ref[...] = dg_acc[...]
        db_ref[...] = db_acc[...]


def _ln_specs(rows, d, rb):
    kw = {} if _VMEM is None else {"memory_space": _VMEM}
    return (pl.BlockSpec((rb, d), lambda i: (i, 0), **kw),
            pl.BlockSpec((1, d), lambda i: (0, 0), **kw),
            pl.BlockSpec((rb, 1), lambda i: (i, 0), **kw))


def _ln_rows(rows: int, d: int) -> int:
    """Largest row block dividing rows whose ~6 f32 block-sized
    temporaries (x, xhat, dy, dyg + outputs) fit the VMEM budget."""
    rb = 512
    while rb > 8 and (rows % rb != 0 or d * rb * 4 * 6 > (8 << 20)):
        rb //= 2
    return rb


def layernorm_pallas_supported(rows: int, d: int) -> bool:
    rb = _ln_rows(rows, d)
    return (pltpu is not None and d % 128 == 0
            and rows % rb == 0 and rb >= 8
            and d * rb * 4 * 6 <= (8 << 20))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def layernorm_pallas(x, gamma, beta, eps: float = 1e-5,
                     interpret: bool = None, save_x: bool = False):
    """(rows, d) layernorm over axis 1; gamma/beta (d,).

    The default backward is output-derived (stats-only residuals — see
    the section comment): the vjp saves only (y, gamma, beta, rstd),
    where y aliases the primal output, so enabling this kernel adds no
    (rows, d) activation memory over the XLA lowering.  ``save_x=True``
    (config ``pallas_ln = x``) restores the round-5 input-saving
    residuals — the precision escape hatch for bf16 configs with
    |beta| >> |gamma| columns — and re-accepts the pinned x.
    """
    y, _ = _ln_fwd_res(x, gamma, beta, eps, interpret, save_x)
    return y


def _ln_fwd_res(x, gamma, beta, eps, interpret, save_x=False):
    if interpret is None:
        interpret = not _on_tpu()
    rows, d = x.shape
    rb = _ln_rows(rows, d)
    assert rows % rb == 0, (
        f"layernorm_pallas: rows={rows} not divisible by row block {rb} "
        "(tail rows would be silently uninitialized); gate with "
        "layernorm_pallas_supported()")
    row_spec, vec_spec, stat_spec = _ln_specs(rows, d, rb)
    y, mean, rstd = pl.pallas_call(
        functools.partial(_ln_fwd_kernel, eps=eps),
        grid=(rows // rb,),
        in_specs=[row_spec, vec_spec, vec_spec],
        out_specs=[row_spec, stat_spec, stat_spec],
        out_shape=[jax.ShapeDtypeStruct((rows, d), x.dtype),
                   jax.ShapeDtypeStruct((rows, 1), jnp.float32),
                   jax.ShapeDtypeStruct((rows, 1), jnp.float32)],
        interpret=interpret,
    )(x, gamma.reshape(1, d), beta.reshape(1, d))
    if save_x:
        return y, (x, gamma, mean, rstd)
    # y in the residuals IS the primal output (same value — the buffer is
    # shared under jit); the input x is deliberately NOT saved
    return y, (y, gamma, beta, rstd)


def _ln_bwd_res(eps, interpret, save_x, res, dy):
    if interpret is None:
        interpret = not _on_tpu()
    rows, d = res[0].shape
    rb = _ln_rows(rows, d)
    assert rows % rb == 0, "layernorm_pallas: unsupported row count"
    row_spec, vec_spec, stat_spec = _ln_specs(rows, d, rb)
    if save_x:
        x, gamma, mean, rstd = res
        kern = _ln_bwd_kernel_x
        args = (x, gamma.reshape(1, d), mean, rstd, dy)
        in_specs = [row_spec, vec_spec, stat_spec, stat_spec, row_spec]
    else:
        y, gamma, beta, rstd = res
        kern = _ln_bwd_kernel
        args = (y, gamma.reshape(1, d), beta.reshape(1, d), rstd, dy)
        in_specs = [row_spec, vec_spec, vec_spec, stat_spec, row_spec]
    dx, dg, db = pl.pallas_call(
        kern,
        grid=(rows // rb,),
        in_specs=in_specs,
        out_specs=[row_spec, vec_spec, vec_spec],
        out_shape=[jax.ShapeDtypeStruct((rows, d), res[0].dtype),
                   jax.ShapeDtypeStruct((1, d), jnp.float32),
                   jax.ShapeDtypeStruct((1, d), jnp.float32)],
        scratch_shapes=_scratch((1, d), (1, d)),
        interpret=interpret,
    )(*args)
    return dx, dg.reshape(d).astype(gamma.dtype), \
        db.reshape(d).astype(gamma.dtype)


layernorm_pallas.defvjp(_ln_fwd_res, _ln_bwd_res)


# --------------------------------------------------------------------------
# Fused master-weight adam update.  The round-5 transformer per-op table
# charges ~47.5 ms/step to convert_reduce fusions: XLA materializes the
# f32 cast of each bf16 weight-grad to HBM before the adam fusion reads
# it, and writes the bf16 cast of the updated master back in a separate
# pass — two extra full-tensor HBM round trips per parameter.  This
# kernel folds the whole update chain (bf16 grad read -> clip -> wd ->
# moments -> master write -> bf16 param write) into ONE VMEM sweep: every
# convert happens in-register, so per parameter the HBM traffic is the
# irreducible read(g, m1, m2, w32) + write(m1, m2, w32, p).
#
# Scope: adam + f32-master (bf16 params) tensors whose size tiles as
# (8k rows, 1024 lanes) — the transformer's big matrices; small/odd
# tensors (gamma/beta vectors, biases) keep the XLA path, where they cost
# nothing.  Opt-in via the `fused_update` engine option until a TPU
# session A/Bs it (the candidate win is the convert_reduce line; the
# adam math itself XLA already fuses well).


_FU_LANES = 1024


def fused_adam_supported(p) -> bool:
    """Tensors the fused update kernel takes: bf16 working params (else
    there is no master and no convert to fuse) tiling as (8k, 1024)."""
    return (pltpu is not None and p.dtype == jnp.bfloat16
            and p.size % (8 * _FU_LANES) == 0)


def _fused_adam_kernel(lr_ref, g_ref, m1_ref, m2_ref, w_ref,
                       p_out, m1_out, m2_out, w_out, *, d1, d2, wd, clip,
                       eps):
    g = g_ref[...].astype(jnp.float32)
    if clip:
        # NaN-zeroing clip (sgd_updater-inl.hpp:15-22), as hyper.clip
        g = jnp.clip(jnp.where(jnp.isnan(g), 0.0, g), -clip, clip)
    w = w_ref[...]
    if wd > 0.0:  # same gate as AdamUpdater._apply32 (wd <= 0 is a no-op)
        g = g - wd * w  # reference adam's sign (adam_updater-inl.hpp:76)
    m1 = m1_ref[...] + d1 * (g - m1_ref[...])
    m2 = m2_ref[...] + d2 * (jnp.square(g) - m2_ref[...])
    w = w - lr_ref[0, 0] * (m1 / (jnp.sqrt(m2) + eps))
    m1_out[...] = m1
    m2_out[...] = m2
    w_out[...] = w
    p_out[...] = w.astype(p_out.dtype)


def fused_adam_pallas(g, m1, m2, w32, lr_t, *, d1, d2, wd=0.0, clip=0.0,
                      out_dtype=jnp.bfloat16, interpret=None):
    """One-sweep adam step on a flattened tensor: returns
    ``(p_new, m1_new, m2_new, w32_new)`` with ``p_new`` in ``out_dtype``.

    ``lr_t`` is the fully bias-corrected step size (a traced f32 scalar,
    fed through SMEM); ``d1``/``d2`` are the reference's DECAY rates.
    Gate with :func:`fused_adam_supported`.
    """
    if interpret is None:
        interpret = not _on_tpu()
    n = w32.size
    r = n // _FU_LANES
    rb = 128
    while rb > 8 and r % rb:
        rb //= 2
    assert r % rb == 0, "fused_adam_pallas: gate with fused_adam_supported"
    sh = (r, _FU_LANES)
    kw = {} if _VMEM is None else {"memory_space": _VMEM}
    row = pl.BlockSpec((rb, _FU_LANES), lambda i: (i, 0), **kw)
    lr_spec = pl.BlockSpec((1, 1), lambda i: (0, 0),
                           memory_space=pltpu.SMEM)
    kern = functools.partial(_fused_adam_kernel, d1=d1, d2=d2, wd=wd,
                             clip=clip, eps=1e-8)
    p_new, m1n, m2n, wn = pl.pallas_call(
        kern,
        grid=(r // rb,),
        in_specs=[lr_spec, row, row, row, row],
        out_specs=[row, row, row, row],
        out_shape=[jax.ShapeDtypeStruct(sh, out_dtype)]
        + [jax.ShapeDtypeStruct(sh, jnp.float32)] * 3,
        interpret=interpret,
    )(jnp.asarray(lr_t, jnp.float32).reshape(1, 1), g.reshape(sh),
      m1.reshape(sh), m2.reshape(sh), w32.reshape(sh))
    shape = w32.shape
    return (p_new.reshape(shape), m1n.reshape(shape),
            m2n.reshape(shape), wn.reshape(shape))
