"""Hand-written Pallas TPU kernels for ops XLA tiles poorly.

The reference proves its op set is user-extensible at the expression level
(``insanity_pooling_layer-inl.hpp:13-49`` defines custom mshadow expressions
in-tree); the TPU analogue is this module: custom Pallas kernels slotted in
behind the same op signatures as the XLA path.

First resident: **LRN** (``lrn_layer-inl.hpp:53-76``).  The cross-channel
windowed reduction sits on a non-minor axis, so the XLA path materialises a
``chpool`` intermediate between two elementwise passes over HBM.  The Pallas
kernel does square → windowed channel sum → normalise in one VMEM-resident
pass per batch row (forward), and the full hand-derived backward

    dx = g·norm^{-β} − 2βα/n · x · chpool(g · x · norm^{-β-1})

in a second single-pass kernel via ``jax.custom_vjp``.

Kernels run in interpreter mode off-TPU so the same code path is unit-tested
on the CPU mesh (pallas_guide: ``interpret=True``).

Measured on TPU v5e (AlexNet lrn1 shape, 512x96x27x27): standalone the Pallas
backward is ~28% faster than the XLA path (5.2ms vs 7.2ms), but inside a full
training step the ``pallas_call`` fusion boundary costs more than the kernel
saves, so dispatch defaults to XLA (``CXXNET_PALLAS_LRN=1`` opts in; see
``nn.lrn``).  The module earns its keep as the custom-kernel extension slot
and as the pattern for future fused kernels.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu import fails on some CPU-only builds; interpret mode needs none
    from jax.experimental.pallas import tpu as pltpu
    _VMEM = pltpu.VMEM
except ImportError:  # pragma: no cover
    pltpu = None
    _VMEM = None


def _on_tpu() -> bool:
    return jax.default_backend() == "tpu"


def _block_spec(nb: int, c: int, hw: int):
    """(NB, C, HW) batch-tile per grid step, resident in VMEM.  NB > 1
    matters: one-row blocks ran 1024 programs per call on AlexNet shapes and
    the per-program overhead swamped the kernel."""
    if _VMEM is None:
        return pl.BlockSpec((nb, c, hw), lambda i: (i, 0, 0))
    return pl.BlockSpec((nb, c, hw), lambda i: (i, 0, 0), memory_space=_VMEM)


def _chwin_sum(sq: jnp.ndarray, nsize: int,
               transpose: bool = False) -> jnp.ndarray:
    """Windowed sum over axis 1 (channels) of an (NB, C, HW) block: element
    j sums sq[j-lo .. j+hi] with lo = nsize//2, hi = nsize-1-lo —
    ``chpool_sum``'s window placement.  ``transpose=True`` swaps lo/hi,
    giving the adjoint window needed by the backward pass for even nsize."""
    c = sq.shape[1]
    lo = nsize // 2
    hi = nsize - 1 - lo
    if transpose:
        lo, hi = hi, lo
    zshape = list(sq.shape)
    acc = sq
    for off in range(1, hi + 1):  # channels above j
        zshape[1] = off
        acc = acc + jnp.concatenate(
            [sq[:, off:], jnp.zeros(zshape, sq.dtype)], axis=1)
    for off in range(1, lo + 1):  # channels below j
        zshape[1] = off
        acc = acc + jnp.concatenate(
            [jnp.zeros(zshape, sq.dtype), sq[:, :c - off]], axis=1)
    return acc


def _norm_pow(norm: jnp.ndarray, beta: float) -> jnp.ndarray:
    """norm^-beta; rsqrt-family fast path for the canonical beta=0.75."""
    if beta == 0.75:
        return jax.lax.rsqrt(norm * jax.lax.sqrt(norm))
    return jnp.power(norm, -beta)


def _lrn_fwd_kernel(x_ref, o_ref, *, nsize, salpha, beta, knorm):
    x = x_ref[...].astype(jnp.float32)
    norm = _chwin_sum(x * x, nsize) * salpha + knorm
    o_ref[...] = (x * _norm_pow(norm, beta)).astype(o_ref.dtype)


def _lrn_bwd_kernel(x_ref, g_ref, dx_ref, *, nsize, salpha, beta, knorm):
    x = x_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32)
    norm = _chwin_sum(x * x, nsize) * salpha + knorm
    npow = _norm_pow(norm, beta)              # norm^-b
    inner = g * x * (npow / norm)             # g x norm^{-b-1}
    dx = g * npow - (2.0 * beta * salpha) * x * _chwin_sum(
        inner, nsize, transpose=True)
    dx_ref[...] = dx.astype(dx_ref.dtype)


def _lrn_batch_tile(n: int, c: int, hw: int, itemsize: int) -> int:
    """Largest batch tile dividing n with a ~1MB input block: the backward
    kernel holds ~6 f32 block-sized temporaries plus the in/out blocks, so
    a bigger block blows the 16MB scoped-vmem limit."""
    nb = max(1, (1 << 20) // max(c * hw * itemsize, 1))
    while n % nb != 0:
        nb -= 1
    return nb


def _call_per_batch(kernel, out_dtype, nsize, salpha, beta, knorm, *args3d,
                    interpret):
    n, c, hw = args3d[0].shape
    nb = _lrn_batch_tile(n, c, hw, args3d[0].dtype.itemsize)
    kern = functools.partial(kernel, nsize=nsize, salpha=salpha, beta=beta,
                             knorm=knorm)
    return pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct((n, c, hw), out_dtype),
        grid=(n // nb,),
        in_specs=[_block_spec(nb, c, hw) for _ in args3d],
        out_specs=_block_spec(nb, c, hw),
        interpret=interpret,
    )(*args3d)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4))
def lrn_pallas(x: jnp.ndarray, nsize: int, alpha: float, beta: float,
               knorm: float) -> jnp.ndarray:
    """LRN over NCHW via the Pallas kernel (same semantics as ``nn.lrn``)."""
    out, _ = _lrn_fwd_res(x, nsize, alpha, beta, knorm)
    return out


def _lrn_fwd_res(x, nsize, alpha, beta, knorm):
    n, c, h, w = x.shape
    x3 = x.reshape(n, c, h * w)
    out = _call_per_batch(_lrn_fwd_kernel, x.dtype, nsize, alpha / nsize,
                          beta, knorm, x3, interpret=not _on_tpu())
    return out.reshape(n, c, h, w), x


def _lrn_bwd_res(nsize, alpha, beta, knorm, res, g):
    x = res
    n, c, h, w = x.shape
    dx = _call_per_batch(_lrn_bwd_kernel, x.dtype, nsize, alpha / nsize,
                         beta, knorm, x.reshape(n, c, h * w),
                         g.reshape(n, c, h * w), interpret=not _on_tpu())
    return (dx.reshape(n, c, h, w),)


lrn_pallas.defvjp(_lrn_fwd_res, _lrn_bwd_res)


# --------------------------------------------------------------------------
# Flash attention: the sequence stack's hot op.  One VMEM-resident pass per
# (batch*head, q-block), online softmax over k-blocks carried in scratch —
# never materialises the (s, s) score matrix.  Backward recomputes scores
# from the saved logsumexp (two kernels: dq over k-blocks, dk/dv over
# q-blocks).  Same math as parallel/ring.dense_attention's chunked path.
#
# Measured on TPU v5e (b4 h8 s8192 d128 bf16, causal): forward 16.5ms vs
# 53ms for the XLA chunked path (3.2x); fwd+bwd 38.5ms, where the XLA
# path's scan-autodiff residuals (per-chunk f32 scores) exceed HBM
# entirely.  Matmul operands stay bf16 (MXU fast path) with f32
# accumulation; block sizes 512x1024 amortise per-program overhead (the
# first cut at 128x128 ran 131k programs and was slower than XLA).

# --------------------------------------------------------------------------
# Strided-conv weight gradient.  XLA computes the wgrad of a strided conv by
# dilating dy with (stride-1) zeros, so for AlexNet conv1 (11x11 / stride 4 /
# cin 3) ~15/16 of the MXU contraction is zeros (~26% efficiency, BASELINE.md
# profile).  This kernel removes the dilation with the space-to-depth
# identity: the stride-s conv equals a stride-1 conv over s2d-rearranged
# input (ops.nn.conv2d_s2d), whose wgrad is a DENSE contraction
#
#     dW_inner[o, (c*s*s)*(kb*kb)] = sum_{n,oh,ow} dy[n,o,oh,ow] *
#                                    x_s2d[n, c*s*s, oh+dh, ow+dw]
#
# evaluated as one (96 x K) @ (K x 432)-shaped MXU matmul per image, with
# the im2col block built tile-wise in VMEM (never materialised to HBM).
# The (co, ci*s*s, kb, kb) result maps back to OIHW outside the kernel.


def _conv_wgrad_kernel(x_ref, dy_ref, o_ref, ob_ref, acc, accb, *, nb, co,
                       cin_b, oh, ow, kb_y, kb_x):
    @pl.when(pl.program_id(0) == 0)
    def _():
        acc[...] = jnp.zeros_like(acc)
        accb[...] = jnp.zeros_like(accb)

    for i in range(nb):
        dy2 = dy_ref[i].reshape(co, oh * ow)
        cols = jnp.concatenate(
            [x_ref[i, :, dh:dh + oh, dw:dw + ow].reshape(cin_b, oh * ow)
             for dh in range(kb_y) for dw in range(kb_x)], axis=0)
        acc[...] += jax.lax.dot_general(
            dy2, cols, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        # bias grad rides along: dy is already in VMEM, so the row-sum is
        # free compared to the separate full-activation reduce XLA emits
        accb[...] += jnp.sum(dy2.astype(jnp.float32), axis=1)[None, :]

    @pl.when(pl.program_id(0) == pl.num_programs(0) - 1)
    def _():
        o_ref[...] = acc[...]
        ob_ref[...] = accb[...]


def conv_wgrad_s2d_pallas(x: jnp.ndarray, dy: jnp.ndarray, *, kh: int,
                          kw: int, stride: int, pad_y: int = 0,
                          pad_x: int = 0, nb: int = 8,
                          interpret: bool = None):
    """Weight + bias gradient of a stride-s 2D conv (no groups), NCHW/OIHW.

    Returns ``(dW (co, ci, kh, kw), db (co,))`` in float32.  Intended for
    the small-input-channel / large-stride geometry class (AlexNet conv1)
    where XLA's dilated-dy formulation starves the MXU; see module comment.
    """
    if interpret is None:
        interpret = not _on_tpu()
    from .nn import s2d_input
    n, c, h, w = x.shape
    _, co, oh, ow = dy.shape
    s = stride
    xs2d, kb_y, kb_x = s2d_input(x, s, kh, kw, oh, ow, pad_y, pad_x)
    cin_b = c * s * s
    while n % nb != 0:
        nb //= 2
    kern = functools.partial(_conv_wgrad_kernel, nb=nb, co=co, cin_b=cin_b,
                             oh=oh, ow=ow, kb_y=kb_y, kb_x=kb_x)
    ncols = cin_b * kb_y * kb_x
    hb, wb = oh - 1 + kb_y, ow - 1 + kb_x
    dw_inner, db = pl.pallas_call(
        kern,
        grid=(n // nb,),
        in_specs=[pl.BlockSpec((nb, cin_b, hb, wb), lambda i: (i, 0, 0, 0)),
                  pl.BlockSpec((nb, co, oh, ow), lambda i: (i, 0, 0, 0))],
        out_specs=[pl.BlockSpec((co, ncols), lambda i: (0, 0)),
                   pl.BlockSpec((1, co), lambda i: (0, 0))],
        out_shape=[jax.ShapeDtypeStruct((co, ncols), jnp.float32),
                   jax.ShapeDtypeStruct((1, co), jnp.float32)],
        scratch_shapes=_scratch((co, ncols), (1, co)),
        interpret=interpret,
    )(xs2d, dy)
    # invert conv2d_s2d's weight layout: columns are ordered
    # (seg=(dh,dw)) x (c, sy, sx); padded taps (dh*s+sy >= kh) are zero in
    # the contraction and sliced away here
    dw6 = dw_inner.reshape(co, kb_y, kb_x, c, s, s)
    dw6 = dw6.transpose(0, 3, 1, 4, 2, 5)  # (co, c, kb_y, sy, kb_x, sx)
    dwp = dw6.reshape(co, c, kb_y * s, kb_x * s)
    return dwp[:, :, :kh, :kw], db[0]


NEG_INF = -1e30


def _fa_blocks(s_len):
    """Block sizes: big blocks amortize per-program overhead; must divide
    the sequence length and satisfy the (8, 128) tile minimum."""
    bq, bk = 512, 1024
    while bq > 128 and s_len % bq != 0:
        bq //= 2
    while bk > 128 and s_len % bk != 0:
        bk //= 2
    return bq, bk


def _causal_mask(s, i, j, bq, bk):
    qpos = i * bq + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    kpos = j * bk + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(qpos >= kpos, s, NEG_INF)


def _fa_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m, l,
                   *, scale, causal, bq, bk):
    i, j = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _():
        acc[...] = jnp.zeros_like(acc)
        m[...] = jnp.full_like(m, NEG_INF)
        l[...] = jnp.zeros_like(l)

    # causal: blocks strictly above the diagonal contribute nothing
    live = (i * bq + bq - 1 >= j * bk) if causal else (j >= 0)

    @pl.when(live)
    def _():
        # keep matmul operands in the input dtype (bf16 hits the MXU's fast
        # path); accumulate in f32 via preferred_element_type
        qb, kb, vb = q_ref[0], k_ref[0], v_ref[0]
        s = jax.lax.dot_general(qb, kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, i, j, bq, bk)
        m_prev = m[...]
        m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        l[...] = l[...] * corr + p.sum(axis=-1, keepdims=True)
        acc[...] = acc[...] * corr + jax.lax.dot_general(
            p.astype(vb.dtype), vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m[...] = m_new

    @pl.when(j == nk - 1)
    def _():
        o_ref[0] = (acc[...] / l[...]).astype(o_ref.dtype)
        lse_ref[0, 0, pl.ds(i * bq, bq)] = (m[...] + jnp.log(l[...]))[:, 0]


def _fa_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                  dq_acc, *, scale, causal, bq, bk):
    i, j = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    live = (i * bq + bq - 1 >= j * bk) if causal else (j >= 0)

    @pl.when(live)
    def _():
        qb, kb = q_ref[0], k_ref[0]
        s = jax.lax.dot_general(qb, kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, i, j, bq, bk)
        p = jnp.exp(s - lse_ref[0, 0, pl.ds(i * bq, bq)][:, None])
        dob = do_ref[0]
        dp = jax.lax.dot_general(dob, v_ref[0],
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0, 0, pl.ds(i * bq, bq)][:, None]) * scale
        dq_acc[...] += jax.lax.dot_general(
            ds.astype(kb.dtype), kb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(j == nk - 1)
    def _():
        dq_ref[0] = dq_acc[...].astype(dq_ref.dtype)


def _fa_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dk_ref, dv_ref, dk_acc, dv_acc, *, scale, causal, bq, bk):
    j, i = pl.program_id(1), pl.program_id(2)  # note: k-block is grid dim 1
    nq = pl.num_programs(2)

    @pl.when(i == 0)
    def _():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    live = (i * bq + bq - 1 >= j * bk) if causal else (i >= 0)

    @pl.when(live)
    def _():
        qb, kb = q_ref[0], k_ref[0]
        s = jax.lax.dot_general(qb, kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, i, j, bq, bk)
        p = jnp.exp(s - lse_ref[0, 0, pl.ds(i * bq, bq)][:, None])
        dob = do_ref[0]
        dv_acc[...] += jax.lax.dot_general(
            p.astype(dob.dtype), dob, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(dob, v_ref[0],
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0, 0, pl.ds(i * bq, bq)][:, None]) * scale
        dk_acc[...] += jax.lax.dot_general(
            ds.astype(qb.dtype), qb, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(i == nq - 1)
    def _():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _scratch(*shapes):
    assert pltpu is not None, "flash attention needs pallas TPU support"
    return [pltpu.VMEM(s, jnp.float32) for s in shapes]


def flash_attention_available(s_len: int, d: int) -> bool:
    return pltpu is not None and s_len % 128 == 0 and d <= 256


def _fa_specs(nbh, s_len, d, bq, bk):
    # row vectors (lse, delta) ride as whole (1, s) blocks pinned per batch
    # row: a (1, bq) block would violate the (8, 128) tile minimum
    q_spec = pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0))
    k_spec = pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0))
    row_spec = pl.BlockSpec((1, 1, s_len), lambda b, i, j: (b, 0, 0))
    return q_spec, k_spec, row_spec


def _fa_fwd(q3, k3, v3, scale, causal, interpret):
    nbh, s_len, d = q3.shape
    bq, bk = _fa_blocks(s_len)
    q_spec, k_spec, row_spec = _fa_specs(nbh, s_len, d, bq, bk)
    kern = functools.partial(_fa_fwd_kernel, scale=scale, causal=causal,
                             bq=bq, bk=bk)
    o, lse = pl.pallas_call(
        kern,
        grid=(nbh, s_len // bq, s_len // bk),
        in_specs=[q_spec, k_spec, k_spec],
        out_specs=[q_spec, row_spec],
        out_shape=[jax.ShapeDtypeStruct(q3.shape, q3.dtype),
                   jax.ShapeDtypeStruct((nbh, 1, s_len), jnp.float32)],
        scratch_shapes=_scratch((bq, d), (bq, 1), (bq, 1)),
        interpret=interpret,
    )(q3, k3, v3)
    return o, lse


def _fa_bwd(q3, k3, v3, o3, lse, g3, scale, causal, interpret):
    nbh, s_len, d = q3.shape
    delta = jnp.sum(g3.astype(jnp.float32) * o3.astype(jnp.float32),
                    axis=-1)[:, None, :]  # (nbh, 1, s)
    bq, bk = _fa_blocks(s_len)
    q_spec, k_spec, row_spec = _fa_specs(nbh, s_len, d, bq, bk)
    dq = pl.pallas_call(
        functools.partial(_fa_dq_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk),
        grid=(nbh, s_len // bq, s_len // bk),
        in_specs=[q_spec, k_spec, k_spec, q_spec, row_spec, row_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct(q3.shape, q3.dtype),
        scratch_shapes=_scratch((bq, d)),
        interpret=interpret,
    )(q3, k3, v3, g3, lse, delta)
    # k-block outer, q-block inner: accumulate dk/dv per k-block
    kq_q_spec = pl.BlockSpec((1, bq, d), lambda b, j, i: (b, i, 0))
    kq_k_spec = pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0))
    kq_row_spec = pl.BlockSpec((1, 1, s_len), lambda b, j, i: (b, 0, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_fa_dkv_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk),
        grid=(nbh, s_len // bk, s_len // bq),
        in_specs=[kq_q_spec, kq_k_spec, kq_k_spec, kq_q_spec,
                  kq_row_spec, kq_row_spec],
        out_specs=[kq_k_spec, kq_k_spec],
        out_shape=[jax.ShapeDtypeStruct(k3.shape, k3.dtype),
                   jax.ShapeDtypeStruct(v3.shape, v3.dtype)],
        scratch_shapes=_scratch((bk, d), (bk, d)),
        interpret=interpret,
    )(q3, k3, v3, g3, lse, delta)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention(q, k, v, causal: bool = False,
                    scale: float = None, interpret: bool = None):
    """Flash attention, (b, h, s, d) -> (b, h, s, d).

    Requires s divisible by 128 (use ``flash_attention_available``);
    ``interpret`` defaults to off-TPU detection so tests run on CPU.
    """
    out, _ = _flash_fwd_res(q, k, v, causal, scale, interpret)
    return out


def _norm_args(q, causal, scale, interpret):
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    if interpret is None:
        interpret = not _on_tpu()
    return scale, interpret


def _flash_fwd_res(q, k, v, causal, scale, interpret):
    scale, interpret = _norm_args(q, causal, scale, interpret)
    b, h, s_len, d = q.shape
    sh3 = (b * h, s_len, d)
    o3, lse = _fa_fwd(q.reshape(sh3), k.reshape(sh3), v.reshape(sh3),
                      scale, causal, interpret)
    return o3.reshape(q.shape), (q, k, v, o3, lse)


def _flash_bwd_res(causal, scale, interpret, res, g):
    q, k, v, o3, lse = res
    scale, interpret = _norm_args(q, causal, scale, interpret)
    b, h, s_len, d = q.shape
    sh3 = (b * h, s_len, d)
    dq, dk, dv = _fa_bwd(q.reshape(sh3), k.reshape(sh3), v.reshape(sh3),
                         o3, lse, g.reshape(sh3), scale, causal, interpret)
    return (dq.reshape(q.shape), dk.reshape(k.shape), dv.reshape(v.shape))


flash_attention.defvjp(_flash_fwd_res, _flash_bwd_res)
