"""Hand-written Pallas TPU kernels for ops XLA tiles poorly.

The reference proves its op set is user-extensible at the expression level
(``insanity_pooling_layer-inl.hpp:13-49`` defines custom mshadow expressions
in-tree); the TPU analogue is this module: custom Pallas kernels slotted in
behind the same op signatures as the XLA path.

First resident: **LRN** (``lrn_layer-inl.hpp:53-76``).  The cross-channel
windowed reduction sits on a non-minor axis, so the XLA path materialises a
``chpool`` intermediate between two elementwise passes over HBM.  The Pallas
kernel does square → windowed channel sum → normalise in one VMEM-resident
pass per batch row (forward), and the full hand-derived backward

    dx = g·norm^{-β} − 2βα/n · x · chpool(g · x · norm^{-β-1})

in a second single-pass kernel via ``jax.custom_vjp``.

Kernels run in interpreter mode off-TPU so the same code path is unit-tested
on the CPU mesh (pallas_guide: ``interpret=True``).

Measured on TPU v5e (AlexNet lrn1 shape, 512x96x27x27): standalone the Pallas
backward is ~28% faster than the XLA path (5.2ms vs 7.2ms), but inside a full
training step the ``pallas_call`` fusion boundary costs more than the kernel
saves, so dispatch defaults to XLA (``CXXNET_PALLAS_LRN=1`` opts in; see
``nn.lrn``).  The module earns its keep as the custom-kernel extension slot
and as the pattern for future fused kernels.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu import fails on some CPU-only builds; interpret mode needs none
    from jax.experimental.pallas import tpu as pltpu
    _VMEM = pltpu.VMEM
except ImportError:  # pragma: no cover
    pltpu = None
    _VMEM = None


def _on_tpu() -> bool:
    return jax.default_backend() == "tpu"


def _block_spec(c: int, hw: int):
    """One batch row (1, C, HW) per grid step, resident in VMEM."""
    if _VMEM is None:
        return pl.BlockSpec((1, c, hw), lambda i: (i, 0, 0))
    return pl.BlockSpec((1, c, hw), lambda i: (i, 0, 0), memory_space=_VMEM)


def _chwin_sum(sq: jnp.ndarray, nsize: int,
               transpose: bool = False) -> jnp.ndarray:
    """Windowed sum over axis 0 (channels) of a (C, HW) block: element j sums
    sq[j-lo .. j+hi] with lo = nsize//2, hi = nsize-1-lo — ``chpool_sum``'s
    window placement.  ``transpose=True`` swaps lo/hi, giving the adjoint
    window needed by the backward pass for even nsize."""
    c = sq.shape[0]
    lo = nsize // 2
    hi = nsize - 1 - lo
    if transpose:
        lo, hi = hi, lo
    acc = sq
    for off in range(1, hi + 1):  # channels above j
        acc = acc + jnp.concatenate(
            [sq[off:], jnp.zeros((off,) + sq.shape[1:], sq.dtype)], axis=0)
    for off in range(1, lo + 1):  # channels below j
        acc = acc + jnp.concatenate(
            [jnp.zeros((off,) + sq.shape[1:], sq.dtype), sq[:c - off]], axis=0)
    return acc


def _norm_pow(norm: jnp.ndarray, beta: float) -> jnp.ndarray:
    """norm^-beta; rsqrt-family fast path for the canonical beta=0.75."""
    if beta == 0.75:
        return jax.lax.rsqrt(norm * jax.lax.sqrt(norm))
    return jnp.power(norm, -beta)


def _lrn_fwd_kernel(x_ref, o_ref, *, nsize, salpha, beta, knorm):
    x = x_ref[0].astype(jnp.float32)
    norm = _chwin_sum(x * x, nsize) * salpha + knorm
    o_ref[0] = (x * _norm_pow(norm, beta)).astype(o_ref.dtype)


def _lrn_bwd_kernel(x_ref, g_ref, dx_ref, *, nsize, salpha, beta, knorm):
    x = x_ref[0].astype(jnp.float32)
    g = g_ref[0].astype(jnp.float32)
    norm = _chwin_sum(x * x, nsize) * salpha + knorm
    npow = _norm_pow(norm, beta)              # norm^-b
    inner = g * x * (npow / norm)             # g x norm^{-b-1}
    dx = g * npow - (2.0 * beta * salpha) * x * _chwin_sum(
        inner, nsize, transpose=True)
    dx_ref[0] = dx.astype(dx_ref.dtype)


def _call_per_batch(kernel, out_dtype, nsize, salpha, beta, knorm, *args3d,
                    interpret):
    n, c, hw = args3d[0].shape
    kern = functools.partial(kernel, nsize=nsize, salpha=salpha, beta=beta,
                             knorm=knorm)
    return pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct((n, c, hw), out_dtype),
        grid=(n,),
        in_specs=[_block_spec(c, hw) for _ in args3d],
        out_specs=_block_spec(c, hw),
        interpret=interpret,
    )(*args3d)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4))
def lrn_pallas(x: jnp.ndarray, nsize: int, alpha: float, beta: float,
               knorm: float) -> jnp.ndarray:
    """LRN over NCHW via the Pallas kernel (same semantics as ``nn.lrn``)."""
    out, _ = _lrn_fwd_res(x, nsize, alpha, beta, knorm)
    return out


def _lrn_fwd_res(x, nsize, alpha, beta, knorm):
    n, c, h, w = x.shape
    x3 = x.reshape(n, c, h * w)
    out = _call_per_batch(_lrn_fwd_kernel, x.dtype, nsize, alpha / nsize,
                          beta, knorm, x3, interpret=not _on_tpu())
    return out.reshape(n, c, h, w), x


def _lrn_bwd_res(nsize, alpha, beta, knorm, res, g):
    x = res
    n, c, h, w = x.shape
    dx = _call_per_batch(_lrn_bwd_kernel, x.dtype, nsize, alpha / nsize,
                         beta, knorm, x.reshape(n, c, h * w),
                         g.reshape(n, c, h * w), interpret=not _on_tpu())
    return (dx.reshape(n, c, h, w),)


lrn_pallas.defvjp(_lrn_fwd_res, _lrn_bwd_res)
