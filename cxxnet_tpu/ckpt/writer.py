"""Async checkpoint writer: snapshot on the train thread, write off it.

The :class:`~cxxnet_tpu.io.device_prefetch.DevicePrefetcher` producer
thread + bounded-queue discipline, in reverse: the train loop is the
producer (it hands a fully host-resident snapshot job over a bounded
queue) and one writer thread is the consumer (npz serialization, crc,
fsync, the manifest-last commit, retention pruning — the file I/O that
used to block the step loop for the whole write).

The D2H pull itself stays ON the train thread (``submit`` receives host
arrays): the jitted train step donates its param/opt/buffer operands, so
a device array handed to another thread would be deleted by the very
next update — only a host copy is safe to write concurrently.  What
moves off-thread is the serialization + disk write, which dominates the
wall for real models on real filesystems.

Failure discipline mirrors the prefetcher's, in the opposite direction:
a writer exception **latches** and re-raises on the train thread at the
next :meth:`submit` / :meth:`poll` / :meth:`close` — a checkpointing run
whose snapshots silently stopped landing is worse than a dead run.
``FAULT_HOOK`` is the crash-injection point for the fault tests: set it
to a callable raising mid-write and the writer dies exactly as a
SIGKILL at that byte would (partial shard files, no manifest).
"""

from __future__ import annotations

import os
import queue
import threading
import time
from typing import Callable, Dict, Optional

import numpy as np

from . import prune_snapshots, write_snapshot

#: test-only crash injection: ``FAULT_HOOK(stage)`` is called after each
#: shard write and before the manifest (stage ``"shard:<name>"`` /
#: ``"manifest"``); raising simulates a kill at that point
FAULT_HOOK: Optional[Callable[[str], None]] = None


class _Job:
    __slots__ = ("path", "shards", "meta", "counter", "keep")

    def __init__(self, path: str, shards: Dict[str, Dict[str, np.ndarray]],
                 meta: dict, counter: int, keep: int):
        self.path = path
        self.shards = shards
        self.meta = meta
        self.counter = counter
        self.keep = keep


class AsyncCheckpointWriter:
    """One writer thread + a bounded queue of pending snapshot jobs.

    ``depth`` bounds in-flight host copies (default 1: at most one
    snapshot being written while the next is prepared — submitting a
    third blocks the train loop, which is backpressure, not loss).
    ``on_done(stats)`` runs on the writer thread after each committed
    snapshot (the task driver emits its ``ckpt`` record there, so the
    record lands even while the loop is mid-dispatch)."""

    def __init__(self, depth: int = 1, on_done=None, tracer=None):
        self._queue: "queue.Queue[Optional[_Job]]" = queue.Queue(
            maxsize=max(int(depth), 1))
        # racelint: latch(write-once by the writer thread; poll() re-raises on the train thread)
        self._failed: Optional[BaseException] = None
        self._on_done = on_done
        # span tracing (monitor/spans.py): per-shard / manifest /
        # prune spans on the writer thread, so the Perfetto export
        # shows the off-thread write next to the train loop's timeline
        if tracer is None:
            from ..monitor import spans as _spans
            tracer = _spans.NULL
        self._tracer = tracer
        # _idle is a Condition over _lock: either spelling acquires the
        # same mutex, so both satisfy the guard
        self._pending = 0  # racelint: guarded-by(self._lock, self._idle)
        self._lock = threading.Lock()
        self._idle = threading.Condition(self._lock)
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="cxxnet-ckpt-writer")
        self._thread.start()

    # ------------------------------------------------------------- producer
    def poll(self) -> None:
        """Re-raise a latched writer failure on the train thread."""
        if self._failed is not None:
            raise self._failed

    def _put(self, item) -> bool:
        """Bounded put that re-checks the failure latch, so a writer
        that died with a full queue can never deadlock the train thread
        (the generation_put discipline, failure-keyed)."""
        while self._failed is None:
            try:
                self._queue.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def submit(self, path: str, shards: Dict[str, Dict[str, np.ndarray]],
               meta: dict, *, counter: int, keep: int) -> float:
        """Enqueue one snapshot job (host arrays only); blocks when the
        bounded queue is full.  Returns the seconds the train thread
        spent blocked here (reported as ``blocked_sec``)."""
        self.poll()
        t0 = time.perf_counter()
        with self._lock:
            self._pending += 1
        if not self._put(_Job(path, shards, meta, counter, keep)):
            self.poll()  # the writer died while we were blocked
        return time.perf_counter() - t0

    def drain(self) -> None:
        """Block until every submitted snapshot committed (or the writer
        failed — then re-raise).  Called before a rollback restore picks
        "the last good snapshot", so an in-flight write can't race the
        scan."""
        with self._idle:
            while self._pending > 0 and self._failed is None:
                self._idle.wait(timeout=0.05)
        self.poll()

    def close(self) -> None:
        """Drain, stop, and join the writer; re-raises a latched
        failure AFTER the thread is joined (callers in finally blocks
        guard it)."""
        if self._thread is not None:
            self._put(None)  # skipped when the writer already died
            self._thread.join()
            self._thread = None
        self.poll()

    # ------------------------------------------------------------- consumer
    def _run(self) -> None:
        while True:
            job = self._queue.get()
            if job is None:
                return
            try:
                t0 = time.perf_counter()
                stats = write_snapshot(job.path, job.shards, job.meta,
                                       fault_hook=FAULT_HOOK,
                                       tracer=self._tracer)
                with self._tracer.span("ckpt_prune", keep=job.keep):
                    pruned = prune_snapshots(
                        os.path.dirname(job.path) or ".", job.keep)
                stats.update(write_sec=time.perf_counter() - t0,
                             path=job.path, counter=job.counter,
                             pruned=pruned)
                if self._on_done is not None:
                    self._on_done(stats)
            except BaseException as e:  # noqa: BLE001 — latch for the loop
                self._failed = e
                with self._idle:
                    self._pending = 0
                    self._idle.notify_all()
                return
            with self._idle:
                self._pending -= 1
                self._idle.notify_all()
