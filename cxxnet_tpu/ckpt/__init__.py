"""Fault-tolerant checkpoints: atomic snapshot dirs + exact-resume manifest.

The reference's production story is a parameter server holding model
state server-side so workers can come and go (mshadow-ps
``ISharedModel``); the TPU-native equivalent is preemption-safe
training.  Before this package a checkpoint was one non-atomic
``np.savez`` (a kill mid-write left a corrupt *newest* snapshot that
``continue = 1`` then loaded) and resume was not trajectory-exact (rng
restarted from the seed, optimizer state was opt-in, the iterator
restarted cold).

A **snapshot** here is a directory ``<model_dir>/NNNN.ckpt/`` written
with a manifest-last protocol:

1. each shard (``params`` / ``buffers`` / ``opt`` / ``acc``) is written
   to ``<shard>.npz.tmp`` and ``os.replace``d to ``<shard>.npz``;
2. ``MANIFEST.json`` is written to a temp name, fsynced, and
   ``os.replace``d into place **last**.

The manifest is the commit marker: a snapshot without one — or whose
shard files fail their recorded size/crc32 — is partial/corrupt and is
*skipped* by ``continue = 1`` (the previous snapshot wins).  A kill at
any byte of the write sequence therefore never loses the previous good
snapshot and never yields a loadable half-written one.

The manifest also carries everything exact resume needs beyond the
arrays: epoch/round counters, the live rng stream (``sample_counter`` +
the raw PRNG key, so a rolled-back-and-reseeded run resumes *its own*
stream, not the seed's), the train-iterator chain state
(``IIterator.state()``), and the sentinel EWMA state.  Arrays are
stored as full host (logical) arrays, so a snapshot taken on one mesh
restores onto any device count — ``load_model`` reshards via the
current trainer's NamedShardings.

See :mod:`.writer` for the async off-thread writer and doc/checkpoint.md
for the format and knobs.
"""

from __future__ import annotations

import json
import os
import shutil
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..analysis.schema import K
from ..utils.serializer import atomic_write

FORMAT_VERSION = 1
MANIFEST = "MANIFEST.json"

#: checkpoint / rollback config keys the task driver consumes
#: (main.LearnTask.set_param); declared here next to their subsystem and
#: appended to TASK_KEYS so the lint registry harvests them.
CKPT_KEYS = (
    K("ckpt_async", "int", lo=0, hi=1,
      help="write snapshots off the training thread (atomic .ckpt dirs)"),
    K("ckpt_keep", "int", lo=1,
      help="retention: keep the newest N .ckpt snapshots"),
    K("rollback", "int", lo=0,
      help="on TrainingDiverged: restore the last good snapshot, reseed "
           "the rng stream, retry up to N times"),
    K("save_opt", "int", lo=0, hi=1,
      help="include optimizer state in snapshots (default 1: exact "
           "resume)"),
    K("ckpt_iter_state", "int", lo=0, hi=1,
      help="carry the train-iterator chain state in snapshots (default "
           "1: cross-round iterator rng/cache state resumes exactly)"),
)


def snapshot_path(model_dir: str, counter: int) -> str:
    return os.path.join(model_dir, f"{counter:04d}.ckpt")


def _crc32(path: str) -> int:
    crc = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                break
            crc = zlib.crc32(chunk, crc)
    return crc & 0xFFFFFFFF


# one copy of the tmp + fsync + os.replace durability protocol, shared
# with the legacy single-file save (utils/serializer.py)
_replace_write = atomic_write


def write_snapshot(path: str, shards: Dict[str, Dict[str, np.ndarray]],
                   meta: dict, fault_hook=None, tracer=None) -> dict:
    """Write one snapshot dir atomically (manifest last).

    ``shards`` maps shard name -> flat ``{key: np.ndarray}`` (the
    serializer's flattened form; bf16 already widened to exact f32 with
    the original dtypes recorded in ``meta``).  ``fault_hook`` is the
    crash-injection point for tests: called as ``fault_hook(stage)``
    after each shard and before the manifest — raising there leaves
    exactly the partial state a kill at that byte would.  ``tracer``
    (a :class:`~cxxnet_tpu.monitor.spans.SpanTracer`) emits one
    ``ckpt_shard`` span per shard (npz + fsync + crc read-back) and a
    ``ckpt_manifest`` span for the commit — the writer-thread timeline
    next to the train loop's in the Perfetto export.

    Returns stats: ``{"bytes": total, "shards": n}``.
    """
    if tracer is None:
        from ..monitor import spans as _spans
        tracer = _spans.NULL
    os.makedirs(path, exist_ok=True)
    # overwriting a committed snapshot (a rollback retry re-saving the
    # same round): drop the manifest FIRST so a kill mid-rewrite leaves
    # an uncommitted dir, not a manifest pointing at mixed-age shards
    mpath = os.path.join(path, MANIFEST)
    if os.path.exists(mpath):
        os.remove(mpath)
    shard_meta: Dict[str, dict] = {}
    total = 0
    for name, arrays in shards.items():
        fpath = os.path.join(path, f"{name}.npz")
        with tracer.span("ckpt_shard", shard=name):
            _replace_write(fpath, lambda f, a=arrays: np.savez(f, **a))
            size = os.path.getsize(fpath)
            # the crc is a deliberate read-BACK of the committed file
            # (not a streaming accumulator: np.savez goes through
            # zipfile, which seeks back to rewrite local headers, so
            # linear crc-on-write would checksum bytes that never
            # land); the manifest certifies what is actually on disk,
            # and the extra read stays on the writer thread, off the
            # training loop
            shard_meta[name] = {"file": f"{name}.npz", "bytes": size,
                                "crc32": _crc32(fpath)}
        total += size
        if fault_hook is not None:
            fault_hook(f"shard:{name}")
    if fault_hook is not None:
        fault_hook("manifest")
    manifest = {"format_version": FORMAT_VERSION, "shards": shard_meta}
    manifest.update(meta)
    with tracer.span("ckpt_manifest"):
        _replace_write(
            mpath, lambda f: f.write(
                json.dumps(manifest, sort_keys=True).encode("utf-8")))
    return {"bytes": total, "shards": len(shard_meta)}


def _read_manifest(path: str) -> Optional[dict]:
    """Parse ``path``'s manifest when present, well-formed, and of this
    format version; None otherwise.  The single copy of the
    open/parse/version check shared by the full validation and the
    ``assume_valid`` fast path."""
    mpath = os.path.join(path, MANIFEST)
    if not os.path.isdir(path) or not os.path.exists(mpath):
        return None
    try:
        with open(mpath, "rb") as f:
            manifest = json.load(f)
    except (OSError, ValueError):
        return None
    if manifest.get("format_version") != FORMAT_VERSION:
        return None
    return manifest


def validate_snapshot(path: str) -> Optional[dict]:
    """Return the manifest when ``path`` is a complete, uncorrupted
    snapshot dir; None otherwise (missing/torn manifest, missing shard,
    size or crc mismatch — the partial/corrupt states a kill leaves)."""
    manifest = _read_manifest(path)
    if manifest is None:
        return None
    for name, sm in (manifest.get("shards") or {}).items():
        fpath = os.path.join(path, sm.get("file", f"{name}.npz"))
        if not os.path.exists(fpath):
            return None
        if os.path.getsize(fpath) != sm.get("bytes"):
            return None
        if _crc32(fpath) != sm.get("crc32"):
            return None
    return manifest


def load_snapshot(path: str, assume_valid: bool = False
                  ) -> Tuple[dict, Dict[str, Dict[str, np.ndarray]]]:
    """Load a validated snapshot: (manifest, shard name -> flat arrays).
    Raises ValueError on a partial/corrupt dir (callers that want to
    skip instead use :func:`validate_snapshot` first).  ``assume_valid``
    skips the full shard crc re-read for callers that JUST ran
    :func:`validate_snapshot` on this path — a multi-GB restore should
    not read every byte twice (the manifest must still exist and
    parse)."""
    manifest = _read_manifest(path) if assume_valid \
        else validate_snapshot(path)
    if manifest is None:
        raise ValueError(
            f"{path}: not a complete checkpoint snapshot (missing/torn "
            "manifest or shard checksum mismatch)")
    shards: Dict[str, Dict[str, np.ndarray]] = {}
    for name, sm in manifest["shards"].items():
        with np.load(os.path.join(path, sm["file"]),
                     allow_pickle=False) as z:
            shards[name] = {k: z[k] for k in z.files}
    return manifest, shards


def list_snapshots(model_dir: str) -> List[Tuple[int, str]]:
    """All snapshot candidates in ``model_dir`` — committed or partial
    ``NNNN.ckpt`` dirs AND legacy ``NNNN.model`` files — as sorted
    ``(counter, path)`` (ascending).  A counter with both forms lists
    the ``.ckpt`` dir last (preferred by newest-first consumers)."""
    out: List[Tuple[int, str]] = []
    try:
        names = os.listdir(model_dir)
    except OSError:
        return out
    for n in names:
        stem, dot, ext = n.rpartition(".")
        if ext not in ("ckpt", "model") or not stem.isdigit():
            continue
        out.append((int(stem), os.path.join(model_dir, n)))
    # .model sorts before .ckpt for equal counters
    out.sort(key=lambda t: (t[0], t[1].endswith(".ckpt")))
    return out


def prune_snapshots(model_dir: str, keep: int) -> int:
    """Retention: delete all but the newest ``keep`` *committed*
    ``.ckpt`` snapshot dirs (legacy ``.model`` files are untouched —
    their retention has always been the user's).  Partial dirs older
    than the newest committed one are swept too (debris from a kill).
    Returns the number of dirs removed."""
    keep = max(int(keep), 1)
    dirs = [(c, p) for c, p in list_snapshots(model_dir)
            if p.endswith(".ckpt")]
    committed = [(c, p) for c, p in dirs
                 if os.path.exists(os.path.join(p, MANIFEST))]
    removed = 0
    drop = {p for _, p in committed[:-keep]} if len(committed) > keep \
        else set()
    if committed:
        newest = committed[-1][0]
        drop |= {p for c, p in dirs
                 if c < newest
                 and not os.path.exists(os.path.join(p, MANIFEST))}
    for p in drop:
        shutil.rmtree(p, ignore_errors=True)
        removed += 1
    return removed
