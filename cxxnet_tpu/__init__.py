"""cxxnet-tpu: a TPU-native deep learning framework with the capabilities of
cxxnet (hihihippp/cxxnet), redesigned for jax/XLA/Pallas on TPU meshes.

Public surface:
* config-file driven CLI: ``python -m cxxnet_tpu config.conf key=val ...``
* :class:`cxxnet_tpu.nnet.trainer.NetTrainer` — the INetTrainer equivalent
* :mod:`cxxnet_tpu.wrapper` — numpy-facing Net / DataIter / train API

The top-level names resolve lazily (PEP 562): importing the package must
NOT pull in jax, so jax-free consumers — ``tools/obsv.py``'s record
paths, the monitor submodules they read — stay fast (~2.7 s of jax
import otherwise, paid on EVERY CLI invocation).  Asserted by
tests/test_tools.py's subprocess test.
"""

__version__ = "0.1.0"

__all__ = ["NetTrainer", "NetConfig", "create_iterator", "__version__"]

_LAZY = {
    "NetTrainer": ("cxxnet_tpu.nnet.trainer", "NetTrainer"),
    "NetConfig": ("cxxnet_tpu.nnet.netconfig", "NetConfig"),
    "create_iterator": ("cxxnet_tpu.io.factory", "create_iterator"),
}


def __getattr__(name):
    if name in _LAZY:
        import importlib
        mod, attr = _LAZY[name]
        return getattr(importlib.import_module(mod), attr)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(_LAZY))
