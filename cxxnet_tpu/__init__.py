"""cxxnet-tpu: a TPU-native deep learning framework with the capabilities of
cxxnet (hihihippp/cxxnet), redesigned for jax/XLA/Pallas on TPU meshes.

Public surface:
* config-file driven CLI: ``python -m cxxnet_tpu config.conf key=val ...``
* :class:`cxxnet_tpu.nnet.trainer.NetTrainer` — the INetTrainer equivalent
* :mod:`cxxnet_tpu.wrapper` — numpy-facing Net / DataIter / train API
"""

__version__ = "0.1.0"

from .nnet.trainer import NetTrainer
from .nnet.netconfig import NetConfig
from .io.factory import create_iterator

__all__ = ["NetTrainer", "NetConfig", "create_iterator", "__version__"]
