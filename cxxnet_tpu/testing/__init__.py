"""Differential-testing harness (host-side form of the PairTest layer).

The reference validates new layer implementations by wiring
``layer[..] = pairtest-<master>-<slave>`` into a config
(``src/layer/pairtest_layer-inl.hpp``); :func:`diff_layers` is the direct
programmatic equivalent for tests and notebooks: build both layers, sync
weights master->slave, run forward and a probe-cotangent backward through
each, and return the relative errors of outputs, input gradients, and
weight gradients.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..layers.base import ForwardContext, LabelInfo, Layer, Shape4
from ..layers.pairtest import (PAIRTEST_RTOL, probe_vjp_compare,
                               relative_error)

__all__ = ["diff_layers", "PAIRTEST_RTOL"]


def diff_layers(master: Layer, slave: Layer, in_shapes: Sequence[Shape4],
                *, key: Optional[jax.Array] = None, dtype=jnp.float32,
                train: bool = True,
                labels: Optional[Dict[str, np.ndarray]] = None,
                loss_scale: float = 1.0) -> Dict[str, float]:
    """Compare two layer implementations on random inputs.

    Returns ``{"fwd_rel_err", "in_grad_rel_err", "wgrad_rel_err",
    "loss_rel_err"}`` (the latter two 0.0 when the layers own no params /
    emit no loss).  Mirrors pairtest_layer-inl.hpp:75-118: outputs, input
    grads and weight grads under one shared cotangent, with slave weights
    synced from the master first (:137-141).
    """
    key = jax.random.PRNGKey(0) if key is None else key
    in_shapes = [tuple(s) for s in in_shapes]
    kin, kparam, kprobe, krng = jax.random.split(key, 4)
    inputs = [jax.random.normal(jax.random.fold_in(kin, i), s, dtype)
              for i, s in enumerate(in_shapes)]
    m_shapes = master.infer_shapes(list(in_shapes))
    s_shapes = slave.infer_shapes(list(in_shapes))
    assert m_shapes == s_shapes, \
        f"diff_layers: output shapes differ: {m_shapes} vs {s_shapes}"
    mp = master.init_params(kparam, list(in_shapes), dtype)
    sp = jax.tree.map(jnp.array, mp)  # master -> slave sync
    mb = master.init_buffers(list(in_shapes))
    sb = slave.init_buffers(list(in_shapes))

    label_info = None
    if labels is not None:
        label_info = LabelInfo(fields={k: jnp.asarray(v, jnp.float32)
                                       for k, v in labels.items()})

    def ctx() -> ForwardContext:
        return ForwardContext(train=train, rng=krng, labels=label_info,
                              loss_scale=loss_scale)

    m_out, s_out, m_loss, s_loss, in_err, w_err = probe_vjp_compare(
        master, slave, mp, sp, mb, sb, inputs, ctx, kprobe)
    return {
        "fwd_rel_err": float(jnp.stack(
            [relative_error(a, b) for a, b in zip(m_out, s_out)]).max()),
        "loss_rel_err": float(relative_error(m_loss, s_loss)),
        "in_grad_rel_err": float(in_err),
        "wgrad_rel_err": float(w_err),
    }
