"""Engine tuning options, settable from the config surface.

These select between measured-equivalent lowerings of the same math
(gradient-semantics variants are called out below).  Each was an
environment variable in earlier rounds; the config file is this
framework's API surface (the reference drives everything through
``name = value`` pairs, SURVEY.md §5.6), so they are first-class config
keys now — ``pool_bwd = eq`` in a .conf does what
``CXXNET_POOL_BWD=eq`` does.  Env vars still work and set the default;
a config key wins over the env var.

Options are read at trace time: set them before the first train/eval
step compiles (the CLI applies config before ``init_model``).  Changing
one mid-run does not retrace already-compiled steps.

| key         | values                     | meaning                        |
|-------------|----------------------------|--------------------------------|
| pool_bwd    | sas (default), eq, gather, | max-pool backward: XLA select- |
|             | auto                       | and-scatter (one argmax per    |
|             |                            | window) vs exact mshadow all-  |
|             |                            | ties unpool (eq == gather);    |
|             |                            | auto = all-ties Pallas where   |
|             |                            | the kernel takes the shape,    |
|             |                            | SAS elsewhere (measured ~equal |
|             |                            | to sas on GoogLeNet; semantics |
|             |                            | vary per pool at ties)         |
| pool_layout | nchw (default), chwn, hwcn | pool compute layout; hwcn =    |
|             |                            | native-layout Pallas kernels   |
|             |                            | (implies all-ties backward)    |
| fast_wgrad  | s2d (default), hwcn,       | wgrad lowering for small-cin   |
|             | pallas, off                | strided convs (AlexNet conv1)  |
| group_conv  | fgc (default), split       | grouped-conv lowering          |
| conv1_fwd   | conv (default), s2d        | forward lowering for the fast- |
|             |                            | wgrad conv class               |
| pallas_lrn  | band (default), hwcn, 1, 0 | LRN lowering (band = MXU      |
|             |                            | banded matmul, round 4)        |
| relu_vjp    | out (default), xla         | relu backward formulation      |
| pool_relu_reorder | 1 (default), 0       | move relu after max pool (and  |
|             |                            | defer conv bias through it) —  |
|             |                            | gradient-equivalent a.e.       |
| pool_relu_fuse | 0 (default), 1          | fuse the deferred relu's       |
|             |                            | backward into the multi-row    |
|             |                            | Pallas pool-backward kernel    |
|             |                            | (mask epilogue on the shared   |
|             |                            | _mp_mr_plan tile plan) where   |
|             |                            | the hwcn kernel takes the      |
|             |                            | shape — implies the all-ties   |
|             |                            | backward for those pools, like |
|             |                            | pool_bwd = auto.  Attacks the  |
|             |                            | GoogLeNet SAS+relu cluster     |
|             |                            | (~15 ms measured vs ~5 modeled |
|             |                            | , BASELINE.md round 5); opt-in |
|             |                            | until a TPU session A/Bs it    |
| conv_sibling_fuse | 0 (default), 1       | run same-input same-geometry   |
|             |                            | convs (inception 1x1 reduces)  |
|             |                            | as one fused conv + slices     |
| concat_virtual | 0 (default), 1          | ch_concat stays a virtual      |
|             |                            | segment tuple; convs consume   |
|             |                            | it as K-sliced sums, pools map |
|             |                            | per segment (layers/base.py    |
|             |                            | ChSegs)                        |
| flash_attn  | 1 (default), 0             | Pallas flash attention on TPU  |
| pallas_ln   | 1 (default), x, 0          | Pallas layernorm kernel in the |
|             |                            | sequence stack.  Default-on    |
|             |                            | since round 6: the backward is |
|             |                            | output-derived (residuals =    |
|             |                            | y/gamma/beta/rstd, no extra    |
|             |                            | (rows, d) buffer — the round-5 |
|             |                            | kernel saved x and OOM'd the   |
|             |                            | d2048 flagship by 0.8G).       |
|             |                            | "x" = input-saving backward    |
|             |                            | (precision escape hatch, pins  |
|             |                            | x).  See doc/pallas_ln.md      |
| fused_update| 0 (default), 1             | one-sweep Pallas adam step for |
|             |                            | big bf16-master tensors: folds |
|             |                            | the bf16->f32 grad convert and |
|             |                            | master->bf16 cast into the     |
|             |                            | update kernel (attacks the     |
|             |                            | ~47.5 ms convert_reduce line). |
|             |                            | Opt-in until a TPU session     |
|             |                            | A/Bs it                        |
| dp_overlap  | 0 (default), 1             | explicit shard_map DP step:    |
|             |                            | gradients reduced in size-     |
|             |                            | targeted buckets, each psum    |
|             |                            | issued at its bucket's grad-   |
|             |                            | ready point inside backward    |
|             |                            | (the async_updater schedule) — |
|             |                            | see doc/multichip.md           |
| dp_bucket_mb| 4 (default), any float     | bucket size target in MiB      |
|             |                            | (reverse layer order)          |
| dp_reduce_dtype | f32 (default), bf16    | bf16 = cast grads to bf16 for  |
|             |                            | the cross-chip reduce, f32     |
|             |                            | master apply (halves comm;     |
|             |                            | trajectories shift)            |
| dp_reduce_at| apply (default), step      | with update_period > 1: reduce |
|             |                            | the accumulated grads once per |
|             |                            | APPLY (1/update_period the     |
|             |                            | comm; reassociates the cross-  |
|             |                            | chip sum) or every micro-step  |
|             |                            | (bitwise-matches the implicit  |
|             |                            | path)                          |

``opts`` is a PROCESS-GLOBAL singleton: every trainer in the process
reads it at trace time, so two trainers with different lowering options
(wrapper API, tests, A/B harnesses) cross-contaminate unless each sets
every option it cares about before its own first compile — see
``experiments/ab.py`` for the discipline.  Each trainer snapshots the
values it read at FIRST TRACE (its first update/eval call — jit traces
lazily, so an init-time snapshot could misreport) into
``trainer.engine_opts_used`` for post-hoc auditing; before the first
trace the attribute is ``None``.
"""

from __future__ import annotations

import os

def _is_positive_float(val: str) -> bool:
    try:
        return float(val) > 0.0
    except ValueError:
        return False


_is_positive_float.expected = "a positive float"


_DEFS = {
    # name: (env var, default, valid values — a tuple of spellings or a
    # predicate for free-form numerics); flash_attn's env var is an
    # inverted bool, special-cased in _Options.__init__
    "pool_bwd": ("CXXNET_POOL_BWD", "sas", ("sas", "eq", "gather", "auto")),
    "pool_layout": ("CXXNET_POOL_LAYOUT", "nchw", ("nchw", "chwn", "hwcn")),
    "fast_wgrad": ("CXXNET_FAST_WGRAD", "s2d",
                   ("s2d", "hwcn", "pallas", "off")),
    "group_conv": ("CXXNET_GROUP_CONV", "fgc", ("fgc", "split")),
    "conv1_fwd": ("CXXNET_CONV1_FWD", "conv", ("conv", "s2d")),
    "pallas_lrn": ("CXXNET_PALLAS_LRN", "band",
                   ("band", "bandconv", "hwcn", "1", "0")),
    "relu_vjp": ("CXXNET_RELU_VJP", "out", ("out", "xla")),
    "pool_relu_reorder": ("CXXNET_POOL_RELU_REORDER", "1", ("1", "0")),
    "pool_relu_fuse": ("CXXNET_POOL_RELU_FUSE", "0", ("1", "0")),
    "conv_sibling_fuse": ("CXXNET_CONV_SIBLING_FUSE", "0", ("1", "0")),
    "concat_virtual": ("CXXNET_CONCAT_VIRTUAL", "0", ("1", "0")),
    "flash_attn": ("CXXNET_NO_FLASH_ATTN", "1", ("1", "0")),
    "pallas_ln": ("CXXNET_PALLAS_LN", "1", ("1", "x", "0")),
    "fused_update": ("CXXNET_FUSED_UPDATE", "0", ("1", "0")),
    # data-parallel bucketed backward-overlapped gradient reduction
    # (parallel/overlap.py, doc/multichip.md)
    "dp_overlap": ("CXXNET_DP_OVERLAP", "0", ("1", "0")),
    "dp_bucket_mb": ("CXXNET_DP_BUCKET_MB", "4", _is_positive_float),
    "dp_reduce_dtype": ("CXXNET_DP_REDUCE_DTYPE", "f32", ("f32", "bf16")),
    "dp_reduce_at": ("CXXNET_DP_REDUCE_AT", "apply", ("apply", "step")),
}


def _valid(name: str, val: str) -> bool:
    valid = _DEFS[name][2]
    return valid(val) if callable(valid) else val in valid


def _expectation(name: str) -> str:
    """Human-readable constraint for error messages (a predicate's repr
    would print a function address)."""
    valid = _DEFS[name][2]
    if callable(valid):
        return getattr(valid, "expected", valid.__name__)
    return f"one of {valid}"


class _Options:
    def __init__(self):
        for name, (env, default, valid) in _DEFS.items():
            if name == "flash_attn":
                # legacy env var is an opt-OUT (CXXNET_NO_FLASH_ATTN=1)
                val = "0" if os.environ.get(env) else "1"
            else:
                val = os.environ.get(env, default)
            assert _valid(name, val), (
                f"env {env} = {val}: expected {_expectation(name)}")
            setattr(self, name, val)

    def set(self, name: str, val: str) -> None:
        # ValueError, not assert: asserts vanish under ``python -O`` and a
        # silently-accepted unknown option is exactly the bug class
        # task=check exists for
        if name not in _DEFS:
            from .analysis.schema import did_you_mean
            sugg = did_you_mean(name, _DEFS)
            raise ValueError(
                f"unknown engine option {name!r}"
                + (f" (did you mean {sugg!r}?)" if sugg else ""))
        if not _valid(name, val):
            raise ValueError(
                f"engine option {name} = {val}: expected {_expectation(name)}")
        setattr(self, name, val)


opts = _Options()


def snapshot() -> dict:
    """Current value of every engine option — the telemetry "run" record
    and ``trainer.engine_opts_used`` both read through this, so audits
    and JSONL sinks agree on spelling."""
    return {k: getattr(opts, k) for k in _DEFS}


def is_engine_option(name: str) -> bool:
    return name in _DEFS


def set_engine_option(name: str, val: str) -> None:
    opts.set(name, val)


def key_specs():
    """Engine options as lint KeySpecs (analysis/registry.py) — the value
    validator is the same ``_valid`` the runtime enforces, so the lint
    pass and ``set_engine_option`` can never disagree."""
    from .analysis.schema import KeySpec

    def make_check(name):
        def check(val):
            if not _valid(name, val):
                return f"expected {_expectation(name)}"
            return None
        return check

    return tuple(
        KeySpec(name=name, kind="str", check=make_check(name),
                help=f"engine option (env {env}, default {default!r})")
        for name, (env, default, _) in _DEFS.items())
