"""Plugin layers adapting external frameworks behind the Layer interface.

Reference: ``src/plugin/caffe_adapter-inl.hpp`` — cxxnet wraps ``caffe::Layer``
objects behind ``ILayer`` so Caffe's implementations can run inside a cxxnet
net, primarily as a known-good oracle for PairTest differential testing
(``caffe_adapter-inl.hpp:23-24``).  The TPU-native analogue wraps **torch**
(CPU) modules: torch is the contemporary known-good reference, and the host
round-trip the reference does per forward/backward (blob copies,
``caffe_adapter-inl.hpp:67-129``) maps onto ``jax.pure_callback`` +
``jax.custom_vjp``.
"""

from .torch_adapter import TorchLayer, torch_available

__all__ = ["TorchLayer", "torch_available"]
