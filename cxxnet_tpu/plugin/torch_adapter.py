"""Torch plugin layer: run torch (CPU) ops inside the traced step.

Reference: ``src/plugin/caffe_adapter-inl.hpp:26-228``.  The caffe adapter
configures the wrapped layer from a ``proto=`` config string and copies blobs
host<->device every Forward/Backprop; weights are exposed to the visitor as
"blobN" (``:45-66``).  Here:

* the wrapped op is chosen with ``op = conv|fullc|relu|sigmoid|tanh`` and
  configured by the SAME hyperparameter keys as the native layer (shape
  inference and parameter init are delegated to the native layer class, so
  param tags/shapes/initialisation are identical — which is exactly what
  makes ``pairtest-conv-torch`` style differential testing work with
  master->slave weight sync);
* the host round-trip is a ``jax.pure_callback`` (forward) plus a
  ``jax.custom_vjp`` whose backward callback runs torch autograd — the
  functional equivalent of the reference's per-step blob copies.

torch never sees TPU memory; XLA stages the transfers around the callback.
"""

from __future__ import annotations

import functools
from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..analysis.schema import K
from ..layers.base import ForwardContext, Layer, Params, Shape4
from ..layers.registry import create_layer

# op name accepted in config -> native layer type it mirrors
_SUPPORTED = {
    "conv": "conv",
    "fullc": "fullc",
    "relu": "relu",
    "sigmoid": "sigmoid",
    "tanh": "tanh",
}


def torch_available() -> bool:
    try:
        import torch  # noqa: F401
        return True
    except ImportError:
        return False


def _torch_forward(op: str, hyper: dict, x: np.ndarray,
                   tags: Tuple[str, ...], param_arrays: Tuple[np.ndarray, ...],
                   need_grads: bool, gout: np.ndarray = None):
    """Run the torch op on host. Returns out, or (gin, *gparams) when
    need_grads (in tag order)."""
    import torch
    import torch.nn.functional as F

    xt = torch.from_numpy(np.asarray(x, np.float32))
    pt = {t: torch.from_numpy(np.asarray(a, np.float32))
          for t, a in zip(tags, param_arrays)}
    if need_grads:
        xt.requires_grad_(True)
        for v in pt.values():
            v.requires_grad_(True)

    if op == "conv":
        out = F.conv2d(xt, pt["wmat"], pt.get("bias"),
                       stride=hyper["stride"],
                       padding=(hyper["pad_y"], hyper["pad_x"]),
                       groups=hyper["num_group"])
    elif op == "fullc":
        out = F.linear(xt.reshape(xt.shape[0], -1), pt["wmat"], pt.get("bias"))
        out = out.reshape(out.shape[0], 1, 1, out.shape[1])
    elif op == "relu":
        out = F.relu(xt)
    elif op == "sigmoid":
        out = torch.sigmoid(xt)
    elif op == "tanh":
        out = torch.tanh(xt)
    else:
        raise ValueError(f"torch adapter: unsupported op {op!r}")

    if not need_grads:
        return out.detach().numpy()
    out.backward(torch.from_numpy(np.asarray(gout, np.float32)))
    grads = [xt.grad.numpy()]
    grads += [pt[t].grad.numpy() if pt[t].grad is not None
              else np.zeros_like(param_arrays[i])
              for i, t in enumerate(tags)]
    return tuple(grads)


class TorchLayer(Layer):
    """``layer[...] = torch`` with ``op = <name>`` (caffe adapter analogue)."""

    type_names = ("torch",)
    extra_config_keys = (
        K("op", "str", help="mirrored native op name"),
    )

    def __init__(self) -> None:
        super().__init__()
        self.op = ""
        self._proxy: Layer = None  # native layer mirrored for shapes/init

    def _ensure_proxy(self) -> Layer:
        if self._proxy is None:
            if self.op not in _SUPPORTED:
                raise ValueError(
                    f"torch adapter: set op = one of {sorted(_SUPPORTED)}")
            self._proxy = create_layer(_SUPPORTED[self.op])
            self._proxy.param = self.param  # share hyperparams
        return self._proxy

    def set_param(self, name: str, val: str) -> None:
        if name == "op":
            self.op = val
            return
        super().set_param(name, val)

    def infer_shapes(self, in_shapes: List[Shape4]) -> List[Shape4]:
        return self._ensure_proxy().infer_shapes(in_shapes)

    def init_params(self, key, in_shapes, dtype=jnp.float32):
        return self._ensure_proxy().init_params(key, in_shapes, dtype)

    def forward(self, params: Params, buffers: Params,
                inputs: List[jnp.ndarray], ctx: ForwardContext):
        self.check_n_inputs(inputs, 1)
        if not torch_available():
            raise RuntimeError("torch adapter requires torch")
        x = inputs[0]
        out_shape = self._ensure_proxy().infer_shapes([tuple(x.shape)])[0]
        hyper = {"stride": self.param.stride, "pad_y": self.param.pad_y,
                 "pad_x": self.param.pad_x, "num_group": self.param.num_group}
        tags = tuple(sorted(params))
        f = _make_callback_fn(self.op, tuple(sorted(hyper.items())), tags,
                              tuple(out_shape))
        out = f(x.astype(jnp.float32),
                tuple(params[t].astype(jnp.float32) for t in tags))
        return [out.astype(x.dtype)], buffers


@functools.lru_cache(maxsize=None)
def _make_callback_fn(op: str, hyper_items: tuple, tags: Tuple[str, ...],
                      out_shape: Tuple[int, ...]):
    """Build the custom_vjp'd host-callback function for one op config.

    Cached on (op, hyperparams, tags, out shape) so retracing reuses the same
    function object (keeps jax's custom_vjp caching effective).
    """
    hyper = dict(hyper_items)

    def _fwd_host(x, *ps):
        return _torch_forward(op, hyper, x, tags, ps, need_grads=False)

    def _bwd_host(x, gout, *ps):
        return _torch_forward(op, hyper, x, tags, ps, need_grads=True,
                              gout=gout)

    @jax.custom_vjp
    def f(x, ps):
        out_sd = jax.ShapeDtypeStruct(out_shape, jnp.float32)
        return jax.pure_callback(_fwd_host, out_sd, x, *ps)

    def f_fwd(x, ps):
        return f(x, ps), (x, ps)

    def f_bwd(res, gout):
        x, ps = res
        out_sds = (jax.ShapeDtypeStruct(x.shape, jnp.float32),) + tuple(
            jax.ShapeDtypeStruct(p.shape, jnp.float32) for p in ps)
        grads = jax.pure_callback(_bwd_host, out_sds, x, gout, *ps)
        return grads[0], tuple(grads[1:])

    f.defvjp(f_fwd, f_bwd)
    return f
